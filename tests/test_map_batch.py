"""Batched Map parity — the L4 composition kernel vs the scalar engine.

Random scalar Maps built from op sequences (the `test/map.rs:13-46` idiom)
are packed into :class:`crdt_tpu.batch.MapBatch`, merged on device, unpacked,
and compared for **full state equality** (clock, entries incl. nested values,
deferred buffers) against the scalar merge — for ``Map<K, MVReg>``,
``Map<K, Orswot>`` and the nested ``Map<K, Map<K2, MVReg>>``
(`/root/reference/test/map.rs:8`).  Plus the CRDT algebra (commutativity,
associativity, idempotence — `test/map.rs:654-730`) directly on the batch
engine, reset-remove (`test/map.rs:136-169`) through the batch path, and the
batched op path vs scalar ``apply``.
"""

import jax.numpy as jnp
import numpy as np
from hypothesis import given
from hypothesis import strategies as st

from crdt_tpu import Dot, Map, MVReg, Orswot, VClock
from crdt_tpu.batch import MapBatch, MVRegKernel, OrswotKernel
from crdt_tpu.batch.val_kernels import MapKernel
from crdt_tpu.config import CrdtConfig
from crdt_tpu.scalar.map import Rm as MapRm, Up
from crdt_tpu.scalar.mvreg import Put
from crdt_tpu.scalar.orswot import Add as OrswotAdd, Rm as OrswotRm
from crdt_tpu.utils.interning import Universe


def small_universe(**kw):
    defaults = dict(
        num_actors=8,
        member_capacity=16,
        deferred_capacity=24,
        mv_capacity=16,
        key_capacity=16,
    )
    defaults.update(kw)
    return Universe(CrdtConfig(**defaults))


actors = st.integers(0, 7)
keys = st.integers(0, 5)
counters = st.integers(1, 6)
vals = st.integers(0, 9)


@st.composite
def mvreg_maps(draw, actor_strategy=actors):
    """Random ``Map<int, MVReg>`` from raw ops (`test/map.rs:13-46` idiom)."""
    m = Map(MVReg)
    for actor, choice, key, val, counter in draw(
        st.lists(
            st.tuples(actor_strategy, st.integers(0, 3), keys, vals, counters),
            max_size=10,
        )
    ):
        clock = VClock.from_iter([(actor, counter)])
        if choice != 1:
            m.apply(Up(dot=Dot(actor, counter), key=key, op=Put(clock=clock, val=val)))
        else:
            m.apply(MapRm(clock=clock, key=key))
    return m


@st.composite
def orswot_maps(draw):
    """Random ``Map<int, Orswot>``."""
    m = Map(Orswot)
    for actor, choice, key, member, counter in draw(
        st.lists(st.tuples(actors, st.integers(0, 3), keys, vals, counters), max_size=10)
    ):
        dot = Dot(actor, counter)
        if choice == 1:
            m.apply(MapRm(clock=dot.to_vclock(), key=key))
        elif choice == 2:
            inner = OrswotRm(clock=dot.to_vclock(), member=member)
            m.apply(Up(dot=dot, key=key, op=inner))
        else:
            m.apply(Up(dot=dot, key=key, op=OrswotAdd(dot=dot, member=member)))
    return m


@st.composite
def nested_maps(draw):
    """Random ``Map<int, Map<int, MVReg>>`` (`test/map.rs:8`)."""
    m = Map(lambda: Map(MVReg))
    for actor, choice, inner_choice, key, ikey, val, counter in draw(
        st.lists(
            st.tuples(actors, st.integers(0, 2), st.integers(0, 2), keys, keys, vals, counters),
            max_size=10,
        )
    ):
        dot = Dot(actor, counter)
        clock = dot.to_vclock()
        if choice == 1:
            m.apply(MapRm(clock=clock, key=key))
        else:
            if inner_choice == 1:
                inner = MapRm(clock=clock, key=ikey)
            else:
                inner = Up(dot=dot, key=ikey, op=Put(clock=clock, val=val))
            m.apply(Up(dot=dot, key=key, op=inner))
    return m


def mv_kernel(uni):
    return MVRegKernel.from_config(uni.config)


def or_kernel(uni):
    return OrswotKernel.from_config(uni.config)


def inner_map_kernel(uni):
    return MapKernel.from_config(uni.config, MVRegKernel.from_config(uni.config))


CASES = [
    (mvreg_maps, mv_kernel),
    (orswot_maps, or_kernel),
    (nested_maps, inner_map_kernel),
]


# -- round-trip -------------------------------------------------------------


@given(mvreg_maps(), orswot_maps(), nested_maps())
def test_roundtrip(m1, m2, m3):
    for m, mk in [(m1, mv_kernel), (m2, or_kernel), (m3, inner_map_kernel)]:
        uni = small_universe()
        back = MapBatch.from_scalar([m], uni, mk(uni)).to_scalar(uni)[0]
        assert back == m


# -- merge parity (the contract) --------------------------------------------


def _merge_parity(a, b, make_kernel):
    uni = small_universe()
    expected = a.clone()
    expected.merge(b)
    kernel = make_kernel(uni)
    got = (
        MapBatch.from_scalar([a], uni, kernel)
        .merge(MapBatch.from_scalar([b], uni, kernel))
        .to_scalar(uni)[0]
    )
    assert got == expected


@given(mvreg_maps(), mvreg_maps())
def test_merge_parity_mvreg(a, b):
    _merge_parity(a, b, mv_kernel)


@given(orswot_maps(), orswot_maps())
def test_merge_parity_orswot(a, b):
    _merge_parity(a, b, or_kernel)


@given(nested_maps(), nested_maps())
def test_merge_parity_nested(a, b):
    _merge_parity(a, b, inner_map_kernel)


# -- algebra on the batch engine (`test/map.rs:654-730`) ---------------------


@given(
    mvreg_maps(st.integers(0, 2)),
    mvreg_maps(st.integers(3, 5)),
    mvreg_maps(st.integers(6, 7)),
)
def test_batch_merge_associative_commutative_idempotent(a, b, c):
    """Replicas get disjoint actor pools, like the reference props — merging
    states that reused a dot for different payloads is undefined behavior and
    quickcheck discards it (`test/map.rs:527-529`, `test/mvreg.rs:120-143`)."""
    uni = small_universe()
    k = mv_kernel(uni)
    ba = MapBatch.from_scalar([a], uni, k)
    bb = MapBatch.from_scalar([b], uni, k)
    bc = MapBatch.from_scalar([c], uni, k)

    ab_c = ba.merge(bb).merge(bc).to_scalar(uni)[0]
    a_bc = ba.merge(bb.merge(bc)).to_scalar(uni)[0]
    assert ab_c == a_bc, "associativity"

    ab = ba.merge(bb).to_scalar(uni)[0]
    ba_ = bb.merge(ba).to_scalar(uni)[0]
    assert ab == ba_, "commutativity"

    aa = ba.merge(ba).to_scalar(uni)[0]
    assert aa == ba.to_scalar(uni)[0], "idempotence"


# -- truncate parity (`map.rs:131-158`) -------------------------------------


@given(mvreg_maps(), st.lists(st.tuples(actors, counters), max_size=5))
def test_truncate_parity(m, clock_pairs):
    uni = small_universe()
    clock = VClock.from_iter(clock_pairs)
    expected = m.clone()
    expected.truncate(clock)

    k = mv_kernel(uni)
    batch = MapBatch.from_scalar([m], uni, k)
    row = np.zeros((1, uni.config.num_actors), dtype=np.asarray(batch.clock).dtype)
    for actor, counter in clock.dots.items():
        row[0, uni.actor_idx(actor)] = counter
    got = batch.truncate(jnp.asarray(row)).to_scalar(uni)[0]
    assert got == expected


# -- batched op path vs scalar apply ----------------------------------------


@given(
    mvreg_maps(),
    st.lists(st.tuples(actors, counters, keys, vals), min_size=1, max_size=6),
)
def test_apply_up_parity(m, ops):
    """One batch = one map per op; each op applied on device vs scalar."""
    uni = small_universe()
    vk = mv_kernel(uni)
    n = len(ops)
    scalars = [m.clone() for _ in range(n)]
    batch = MapBatch.from_scalar(scalars, uni, vk)

    actor_idx = jnp.asarray([uni.actor_idx(a) for a, _, _, _ in ops], dtype=jnp.int32)
    counter = jnp.asarray([c for _, c, _, _ in ops], dtype=batch.clock.dtype)
    key_id = jnp.asarray([uni.member_id(key) for _, _, key, _ in ops], dtype=jnp.int32)
    a_dim = uni.config.num_actors
    op_clocks = np.zeros((n, a_dim), dtype=np.asarray(batch.clock).dtype)
    for i, (a, c, _, _) in enumerate(ops):
        op_clocks[i, uni.actor_idx(a)] = c
    op_vals = jnp.asarray(
        [uni.member_id(v) for _, _, _, v in ops], dtype=batch.clock.dtype
    )
    op_clocks = jnp.asarray(op_clocks)

    got = batch.apply_up(
        actor_idx, counter, key_id, "apply_put", (op_clocks, op_vals)
    ).to_scalar(uni)

    for i, (a, c, key, val) in enumerate(ops):
        clock = VClock.from_iter([(a, c)])
        scalars[i].apply(Up(dot=Dot(a, c), key=key, op=Put(clock=clock, val=val)))
        assert got[i] == scalars[i], f"op {i}"


@given(
    mvreg_maps(),
    st.lists(st.tuples(st.lists(st.tuples(actors, counters), max_size=3), keys), min_size=1, max_size=6),
)
def test_apply_rm_parity(m, rms):
    uni = small_universe()
    k = mv_kernel(uni)
    n = len(rms)
    scalars = [m.clone() for _ in range(n)]
    batch = MapBatch.from_scalar(scalars, uni, k)

    a_dim = uni.config.num_actors
    rm_clocks = np.zeros((n, a_dim), dtype=np.asarray(batch.clock).dtype)
    for i, (pairs, _) in enumerate(rms):
        vc = VClock.from_iter(pairs)
        for actor, counter in vc.dots.items():
            rm_clocks[i, uni.actor_idx(actor)] = counter
    key_id = jnp.asarray([uni.member_id(key) for _, key in rms], dtype=jnp.int32)

    got = batch.apply_rm(jnp.asarray(rm_clocks), key_id).to_scalar(uni)

    for i, (pairs, key) in enumerate(rms):
        scalars[i].apply(MapRm(clock=VClock.from_iter(pairs), key=key))
        assert got[i] == scalars[i], f"rm {i}"


# -- reset-remove through the batch engine (`test/map.rs:136-169`) -----------


def test_reset_remove_batch():
    """Concurrent remove-map-entry vs nested update: the entry survives but
    edits seen by the remover are gone — replayed through MapBatch."""
    m1 = Map(MVReg)
    ctx = m1.get(101).derive_add_ctx("A")
    m1.apply(m1.update(101, ctx, lambda r, c: r.set(1, c)))

    m2 = m1.clone()
    # A removes the key; B concurrently writes a fresh value under it
    rm_op = m1.rm(101, m1.get(101).derive_rm_ctx())
    up_op = m2.update(101, m2.get(101).derive_add_ctx("B"), lambda r, c: r.set(2, c))
    m1.apply(rm_op)
    m2.apply(up_op)

    expected = m1.clone()
    expected.merge(m2)
    assert expected.get(101).val is not None
    assert expected.get(101).val.read().val == [2]  # A's edit is gone, B's survives

    uni = small_universe()
    k = mv_kernel(uni)
    got = (
        MapBatch.from_scalar([m1], uni, k)
        .merge(MapBatch.from_scalar([m2], uni, k))
        .to_scalar(uni)[0]
    )
    assert got == expected
