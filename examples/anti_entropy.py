"""End-to-end anti-entropy walkthrough: ops over the wire, batched
joins on device, and a sharded collective join over a device mesh.

The reference library stops at "serialize the op/state and transport it
however you like" (`/root/reference/src/lib.rs:62-83`; its only example,
`examples/pprint.rs`, pretty-prints two values).  This example shows the
same protocol end to end in the TPU-native framework, then scales it:

  1. op-based replication between scalar replicas over `to_binary` bytes
     (read → derive ctx → mutate → ship — `/root/reference/src/ctx.rs:5-9`);
  2. a causally-future remove that buffers in the deferred table and
     resolves after anti-entropy (`orswot.rs:195-211`);
  3. the same fleet packed into dense batches and joined on device with
     one pairwise-tree reduction (`OrswotBatch.join_fleet`);
  4. the join re-run as a *collective* over a device mesh — one replica
     shard per device, merge as the all-reduce combiner riding ICI
     (`parallel.allgather_join_orswot`).

Run on CPU with a virtual 8-device mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/anti_entropy.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
# Force CPU unless the caller explicitly opts into an accelerator with
# CRDT_EXAMPLE_PLATFORM: dev environments PRESET JAX_PLATFORMS to a
# remote-accelerator plugin whose backend init can block indefinitely
# when its tunnel is down, so deferring to the ambient value (setdefault)
# would hang this walkthrough.  The config.update mirrors
# tests/conftest.py — the env var alone is not honored once the ambient
# plugin has registered.
platform = os.environ.get("CRDT_EXAMPLE_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = platform

import jax  # noqa: E402

jax.config.update("jax_platforms", platform)

import numpy as np  # noqa: E402

from crdt_tpu import Orswot, from_binary, to_binary  # noqa: E402
from crdt_tpu.batch import OrswotBatch  # noqa: E402
from crdt_tpu.config import CrdtConfig  # noqa: E402
from crdt_tpu.utils.interning import Universe  # noqa: E402


def step1_op_replication():
    """Three replicas exchanging serialized ops (no shared memory)."""
    replicas = {name: Orswot() for name in ("alice", "bob", "carol")}

    def broadcast(op):
        wire = to_binary(op)  # what would cross the network
        for r in replicas.values():
            r.apply(from_binary(wire))

    a = replicas["alice"]
    broadcast(a.add("apple", a.value().derive_add_ctx("alice")))
    b = replicas["bob"]
    broadcast(b.add("pear", b.value().derive_add_ctx("bob")))
    values = {frozenset(r.value().val) for r in replicas.values()}
    assert values == {frozenset({"apple", "pear"})}
    print("1. op replication over the wire:", sorted(a.value().val))
    return replicas


def step2_deferred_remove(replicas):
    """A remove whose context is causally ahead buffers, then resolves."""
    carol = replicas["carol"]
    ctx = carol.contains("apple").derive_rm_ctx()
    ctx.clock.witness("dave", 1)  # dave's write hasn't reached carol yet
    rm = carol.remove("apple", ctx)

    bob = replicas["bob"]
    bob.apply(rm)
    assert len(bob.deferred) == 1  # buffered, not lost (orswot.rs:195-203)

    # dave's write arrives; anti-entropy flushes the buffered remove
    dave = Orswot()
    dave.apply(dave.add("fig", dave.value().derive_add_ctx("dave")))
    bob.merge(dave)
    bob.merge(Orswot())  # defer plunger (test/orswot.rs:61-62)
    assert "apple" not in bob.value().val and "fig" in bob.value().val
    print("2. deferred remove resolved after anti-entropy:",
          sorted(bob.value().val))


def step3_batched_join():
    """A fleet of replicas × objects joined as one device reduction."""
    rng = np.random.RandomState(0)
    # counter_bits=32 is the TPU-native width; u64 is the parity default
    uni = Universe(CrdtConfig(num_actors=8, member_capacity=16,
                              deferred_capacity=4, counter_bits=32))
    n_objects, n_replicas = 256, 8
    fleets = []
    for r in range(n_replicas):
        row = []
        for i in range(n_objects):
            s = Orswot()
            for j in range(int(rng.randint(1, 5))):
                member = f"item{(i * 7 + j * 3) % 11}"
                s.apply(s.add(member, s.value().derive_add_ctx(f"node{r}")))
            row.append(s)
        fleets.append(OrswotBatch.from_scalar(row, uni))

    joined = OrswotBatch.join_fleet(fleets)  # log-depth pairwise tree
    sets = joined.value_sets(uni)
    print(f"3. batched join: {n_replicas} fleets × {n_objects} objects → "
          f"e.g. object 0 = {sorted(sets[0])}")
    return uni, fleets, sets


def step4_collective_join(uni, fleets, expected_sets):
    """The same join as a mesh collective: one replica shard per device,
    merge as the all-reduce combiner (the ICI path on real hardware)."""
    import jax
    import jax.numpy as jnp

    from crdt_tpu.parallel import allgather_join_orswot, make_mesh

    n_dev = len(jax.devices())
    if n_dev < len(fleets):
        print(f"4. collective join skipped ({n_dev} devices < {len(fleets)})")
        return
    mesh = make_mesh({"replicas": len(fleets)})
    stacked = OrswotBatch(
        clock=jnp.stack([f.clock for f in fleets]),
        ids=jnp.stack([f.ids for f in fleets]),
        dots=jnp.stack([f.dots for f in fleets]),
        d_ids=jnp.stack([f.d_ids for f in fleets]),
        d_clocks=jnp.stack([f.d_clocks for f in fleets]),
    )
    joined = allgather_join_orswot(stacked, mesh, axis="replicas")
    # every device holds the same joined state; check shard 0
    first = OrswotBatch(
        clock=joined.clock[0], ids=joined.ids[0], dots=joined.dots[0],
        d_ids=joined.d_ids[0], d_clocks=joined.d_clocks[0],
    )
    assert first.value_sets(uni) == expected_sets
    print(f"4. collective join over a {len(fleets)}-device mesh axis "
          "matches the batched join on every shard")


def step5_typed_collective_joins():
    """Every register/set type has its own mesh collective: LWW joins by
    marker-argmax (equal-marker conflicts surface host-side,
    `lwwreg.rs:56-66`), MVReg by antichain gather-fold (concurrent values
    all survive, `mvreg.rs:121-153`)."""
    import jax
    import jax.numpy as jnp

    from crdt_tpu.batch import LWWRegBatch, MVRegBatch
    from crdt_tpu.parallel import (
        allgather_join_lww, allgather_join_mvreg, make_mesh,
    )
    from crdt_tpu.scalar.lwwreg import LWWReg
    from crdt_tpu.scalar.mvreg import MVReg

    n_dev = len(jax.devices())
    if n_dev < 8:
        print(f"5. typed collective joins skipped ({n_dev} devices < 8)")
        return
    mesh = make_mesh({"replicas": 8})
    uni = Universe(CrdtConfig(num_actors=8, mv_capacity=8))

    # LWW: 8 replicas each last-wrote one register at a distinct time
    fleet = [[LWWReg(val=f"edit-{r}", marker=100 + r)] for r in range(8)]
    stack = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[LWWRegBatch.from_scalar(row, uni) for row in fleet],
    )
    joined, conflict = allgather_join_lww(stack, mesh)
    assert not bool(jnp.any(conflict))
    winner = LWWRegBatch(
        vals=joined.vals[0], markers=joined.markers[0]
    ).to_scalar(uni)[0]
    assert winner.val == "edit-7"  # the largest marker wins everywhere

    # MVReg: 8 concurrent writers — the join keeps all eight values
    regs = []
    for r in range(8):
        reg = MVReg()
        reg.apply(reg.set(f"draft-{r}", reg.read().derive_add_ctx(r)))
        regs.append(reg)
    stack = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[MVRegBatch.from_scalar([reg], uni) for reg in regs],
    )
    joined_mv = allgather_join_mvreg(stack, mesh)
    survivors = MVRegBatch(
        clocks=joined_mv.clocks[0], vals=joined_mv.vals[0]
    ).to_scalar(uni)[0]
    assert len(survivors.read().val) == 8
    print("5. typed collective joins: LWW marker-argmax winner "
          f"{winner.val!r}; MVReg keeps all {len(survivors.read().val)} "
          "concurrent values")


def step6_elastic_regrowth():
    """Static capacities are the TPU build's one concession; the executor
    makes them elastic — an overflowing join regrows the padded axes and
    requeues (idempotent merge makes the retry safe)."""
    from crdt_tpu.parallel import JoinExecutor, JoinStats

    uni = Universe(CrdtConfig(num_actors=8, member_capacity=2,
                              deferred_capacity=2))
    fleets = []
    for r in range(4):
        s = Orswot()
        for j in range(2):
            s.apply(s.add(f"m{r}-{j}", s.value().derive_add_ctx(f"node{r}")))
        fleets.append(OrswotBatch.from_scalar([s], uni))

    stats = JoinStats()
    joined = JoinExecutor().join_all(fleets, stats=stats)
    sets = joined.value_sets(uni)
    assert len(sets[0]) == 8  # union exceeded capacity 2, nothing lost
    print(f"6. elastic regrowth: capacity 2 → "
          f"{stats.final_member_capacity} after "
          f"{stats.overflow_regrows} regrow(s); all {len(sets[0])} members "
          "survived")


def step7_bulk_wire_loop():
    """State-based replication at fleet scale, zero Python objects in the
    hot path: wire blobs (`to_binary` payloads) decode straight into
    dense planes through the native parallel codec, merge on device, and
    encode back to blobs byte-identical to `to_binary` — ~1M+ objects/s
    each way vs ~170k/~50k for the per-object walk (`PERF.md`).  Needs an
    identity universe: int actors/members map to themselves, so there is
    no host-side interning state at all."""
    rng = np.random.RandomState(7)
    uni = Universe.identity(CrdtConfig.tpu_default(
        num_actors=8, member_capacity=8, deferred_capacity=4,
    ))
    n = 2000
    # replica A's fleet arrives as wire blobs (as if from the network)
    incoming = []
    for i in range(n):
        s = Orswot()
        for j in range(int(rng.randint(1, 4))):
            s.apply(s.add(int(rng.randint(0, 100)),
                          s.value().derive_add_ctx(j % 4)))
        incoming.append(to_binary(s))

    local = OrswotBatch.from_wire(incoming, uni)     # native parallel decode
    mine = OrswotBatch.zeros(n, uni)                 # this node starts empty
    merged = local.merge(mine, impl=uni.config.merge_impl)
    outgoing = merged.to_wire(uni)                   # native parallel encode
    # byte-faithful means byte-faithful: what we ship IS what to_binary
    # would have produced for the merged scalars
    assert outgoing[:64] == [to_binary(s) for s in merged.to_scalar(uni)[:64]]
    # and a plain-Python peer decodes it
    peer = from_binary(outgoing[0])
    assert peer.value().val == from_binary(incoming[0]).value().val
    print(f"7. bulk wire loop: {n} blobs in -> device merge -> {n} blobs "
          "out, byte-identical to the scalar codec")
    return uni, n, incoming


def step8_pipelined_wire_loop(uni, n, incoming):
    """The sustained form of step 7 — the SAME loop the bench times
    (`crdt_tpu.batch.wireloop.PipelinedWireLoop`, one implementation for
    bench and examples): reused staging buffers instead of a fresh plane
    set per fleet (the round-5 e2e ingest collapse was exactly that
    allocation churn, PERF.md), with a background thread parsing the
    next fleet while the current one folds.  The result dict carries the
    per-stage times and the native-vs-fallback blob accounting the bench
    JSON publishes as ``native_fraction``."""
    from crdt_tpu.batch.wireloop import PipelinedWireLoop

    # two replica fleets of the same objects: fleet 0 is the step-7
    # traffic, fleet 1 a second replica's copy arriving in the same
    # anti-entropy round
    loop = PipelinedWireLoop(uni)
    res = loop.run([[incoming, incoming]])
    # fold of two identical replicas + plunger == scalar self-merge
    # (byte-level spot check on object 0 — the digest pass below is the
    # fleet-wide oracle)
    acc = from_binary(incoming[0])
    acc.merge(from_binary(incoming[0]))
    acc.merge(acc.clone())
    assert res["out_blobs"][0] == to_binary(acc)

    # convergence oracle: one digest pass per replica instead of a full
    # value() comparison — after the round, every replica that merges
    # the fold output must land on an identical digest vector (one
    # jitted kernel + an N×8-byte compare; a 1M-object fleet checks in
    # one launch where per-object value() comparison walks the heap)
    from crdt_tpu.sync import digest as sync_digest

    folded = OrswotBatch.from_wire(res["out_blobs"], uni)
    want = sync_digest.digest_of(folded)
    for r, blobs in enumerate((incoming, incoming)):
        replica = OrswotBatch.from_wire(blobs, uni).merge(folded)
        replica = replica.merge(replica)  # defer plunger
        got = sync_digest.digest_of(replica)
        assert np.array_equal(got, want), (
            f"replica {r} digest vector diverged after anti-entropy"
        )
    nf = res["ingest_native_fraction"]
    print(f"8. pipelined wire loop ({res['fold_path']} fold, "
          f"{res['pipeline']}): {res['merges']} replica-objects in "
          f"{res['e2e_s']:.3f}s, ingest native_fraction={nf}; all replica "
          "digest vectors converged")


def step9_causal_gc(uni, n, incoming):
    """Causal GC closes the loop: a fleet that regrew through step 6's
    elastic ladder carries padding (and settled-but-unswept tombstone
    rows) forever — until the GC layer (`crdt_tpu.gc`) settles the
    deferred tables and re-packs the slot axes back down the ladder.
    Compaction reclaims REPRESENTATION, never state: the digest vector
    — the same convergence oracle step 8 used — is byte-identical
    before and after."""
    from crdt_tpu.gc import GcEngine, GcPolicy
    from crdt_tpu.sync import digest as sync_digest

    fleet = OrswotBatch.from_wire(incoming, uni)
    fleet = fleet.merge(fleet)  # canonical (plunged) form
    # as a burst would leave it: slot axes regrown 4x above the config
    cfg = uni.config
    fleet = fleet.with_capacity(cfg.member_capacity * 4,
                                cfg.deferred_capacity * 4)
    before = sync_digest.digest_of(fleet)
    bytes_before = sum(
        x.nbytes for x in (fleet.clock, fleet.ids, fleet.dots,
                           fleet.d_ids, fleet.d_clocks))

    engine = GcEngine(GcPolicy(interval_rounds=1))
    compacted, report = engine.collect(fleet, universe=uni)
    after = sync_digest.digest_of(compacted)
    assert np.array_equal(np.asarray(before), np.asarray(after)), (
        "causal GC changed the digest vector — compaction must be "
        "representation-only"
    )
    assert report.reclaimed_bytes > 0 and report.shrunk
    print(f"9. causal GC: member capacity "
          f"{report.member_capacity[0]} -> {report.member_capacity[1]}, "
          f"{report.reclaimed_bytes} of {bytes_before} plane bytes "
          f"reclaimed; digest vector byte-identical before/after")


def main():
    replicas = step1_op_replication()
    step2_deferred_remove(replicas)
    uni, fleets, sets = step3_batched_join()
    step4_collective_join(uni, fleets, sets)
    step5_typed_collective_joins()
    step6_elastic_regrowth()
    uni, n, incoming = step7_bulk_wire_loop()
    step8_pipelined_wire_loop(uni, n, incoming)
    step9_causal_gc(uni, n, incoming)
    print("anti-entropy walkthrough: OK")


if __name__ == "__main__":
    main()
