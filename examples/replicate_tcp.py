"""Two-process anti-entropy over real TCP — the full replication loop.

The reference deliberately ships no transport: "serialize state or op,
transport however you like, merge/apply on the other side"
(`/root/reference/src/lib.rs:62-83`; the ctx protocol docs even sketch
the ship-to-client pattern, `/root/reference/src/ctx.rs:5-9`).  This
example IS that missing piece, built on the framework's bulk wire
codec: two OS processes, each owning a replica of the same object
partition, exchange state over a localhost TCP socket and converge.

Per peer:

1. build N ``Orswot`` objects and apply local ops under its own actor
   (op path: ``value().derive_add_ctx(actor)`` → ``add`` → ``apply``,
   `/root/reference/src/orswot.rs:64-84` semantics);
2. pack the fleet into dense planes (``OrswotBatch.from_scalar``) and
   egress wire blobs with the native bulk codec (``to_wire`` — each
   blob is byte-identical to ``to_binary`` of the scalar object);
3. swap blobs over TCP (length-prefixed frames);
4. ``from_wire`` the peer's state and ``merge`` on the batch engine;
   one extra self-merge acts as the defer plunger;
5. print a digest of every object's ``value()``; both sides must match.

Run it:

    python examples/replicate_tcp.py            # spawns both peers
    python examples/replicate_tcp.py --objects 1000

(`--platform cpu` forces the CPU backend, e.g. when no TPU is
reachable; the kernels are platform-agnostic.)
"""

from __future__ import annotations

import argparse
import hashlib
import os
import socket
import struct
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _frame_send(sock: socket.socket, blobs: list[bytes]) -> None:
    sock.sendall(struct.pack("<I", len(blobs)))
    for b in blobs:
        sock.sendall(struct.pack("<I", len(b)))
        sock.sendall(b)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def _frame_recv(sock: socket.socket) -> list[bytes]:
    (count,) = struct.unpack("<I", _recv_exact(sock, 4))
    out = []
    for _ in range(count):
        (ln,) = struct.unpack("<I", _recv_exact(sock, 4))
        out.append(_recv_exact(sock, ln))
    return out


def _build_fleet(n_objects: int, actor: int, seed: int):
    """N scalar Orswots with local op histories under ``actor``."""
    import numpy as np

    from crdt_tpu import Orswot

    rng = np.random.RandomState(seed)
    fleet = []
    for i in range(n_objects):
        o = Orswot()
        for _ in range(int(rng.randint(1, 5))):
            member = int(rng.randint(0, 64))
            o.apply(o.add(member, o.value().derive_add_ctx(actor)))
        if i % 7 == 0:  # a causal remove on some objects
            read = o.value()
            if read.val:
                m = sorted(read.val)[0]
                o.apply(o.remove(m, o.contains(m).derive_rm_ctx()))
        fleet.append(o)
    return fleet


def _digest(batch, universe) -> str:
    """Canonical content digest of every object's value() set."""
    h = hashlib.sha256()
    for o in batch.to_scalar(universe):
        h.update(repr(sorted(o.value().val)).encode())
    return h.hexdigest()[:16]


def peer(role: str, port: int, n_objects: int, platform: str | None) -> str:
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)

    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.utils.interning import Universe

    # identity universe: int actors/members -> the native C++ bulk codec
    # parses/serializes the blobs with zero host-side interning state
    uni = Universe.identity(CrdtConfig(num_actors=8, member_capacity=32,
                                       deferred_capacity=8, counter_bits=32))
    actor = 1 if role == "server" else 2
    mine = OrswotBatch.from_scalar(
        _build_fleet(n_objects, actor, seed=actor), uni
    )

    if role == "server":
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", port))
        srv.listen(1)
        srv.settimeout(120)  # a peer that never comes must not orphan us
        sock, _ = srv.accept()
        srv.close()
    else:
        # the peers race at startup: retry until the server's bind lands
        import time

        deadline = time.monotonic() + 120
        while True:
            try:
                sock = socket.create_connection(("127.0.0.1", port), timeout=10)
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.5)

    with sock:
        # state-based anti-entropy: swap full state, merge, done — merge
        # idempotence/commutativity makes ordering and redelivery safe
        # (`/root/reference/src/traits.rs:9-12,36`)
        _frame_send(sock, mine.to_wire(uni))
        theirs = OrswotBatch.from_wire(_frame_recv(sock), uni)
        merged = mine.merge(theirs)
        merged = merged.merge(merged)  # defer plunger

        dig = _digest(merged, uni)
        # confirm convergence: exchange digests
        _frame_send(sock, [dig.encode()])
        peer_dig = _frame_recv(sock)[0].decode()

    status = "CONVERGED" if dig == peer_dig else "DIVERGED"
    print(f"{role}: {n_objects} objects  digest={dig}  peer={peer_dig}  {status}")
    return status


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("role", nargs="?", default="demo",
                    choices=["demo", "server", "client"])
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--objects", type=int, default=64)
    ap.add_argument("--platform", default=None,
                    help="force a JAX platform (e.g. cpu)")
    args = ap.parse_args()

    if args.role != "demo":
        if not args.port:
            ap.error("server/client roles need --port")
        return 0 if peer(args.role, args.port, args.objects, args.platform) == "CONVERGED" else 1

    # demo: spawn both peers as real OS processes
    import subprocess

    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]

    base = [sys.executable, os.path.abspath(__file__)]
    extra = ["--port", str(port), "--objects", str(args.objects)]
    if args.platform:
        extra += ["--platform", args.platform]
    srv = subprocess.Popen(base + ["server"] + extra)
    cli = subprocess.Popen(base + ["client"] + extra)
    rc = srv.wait() | cli.wait()
    print("demo:", "CONVERGED" if rc == 0 else "DIVERGED/FAILED")
    return rc


if __name__ == "__main__":
    sys.exit(main())
