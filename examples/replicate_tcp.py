"""Two-process anti-entropy over real TCP — digest-driven delta sync.

The reference deliberately ships no transport: "serialize state or op,
transport however you like, merge/apply on the other side"
(`/root/reference/src/lib.rs:62-83`; the ctx protocol docs even sketch
the ship-to-client pattern, `/root/reference/src/ctx.rs:5-9`).  This
example IS that missing piece: two OS processes, each owning a replica
of the same object partition, reconcile over a localhost TCP socket
through :class:`crdt_tpu.sync.SyncSession` — digest vectors first, then
only the diverged rows' wire blobs, so bytes-on-wire is O(divergence)
instead of O(total state).

Per peer:

1. build N ``Orswot`` objects from a SHARED op history (same seed), then
   apply divergent local ops under its own actor to a small fraction of
   objects — the realistic anti-entropy shape: replicas agree on almost
   everything;
2. pack the fleet into dense planes (``OrswotBatch.from_scalar``);
3. run a ``SyncSession`` over the socket: every frame is length-prefixed
   and carries a 1-byte protocol version, so a mixed-version peer fails
   loudly (`SyncProtocolError`) instead of misparsing;
4. print the per-phase wire accounting (digest vs delta bytes) and the
   convergence verdict from the session's digest verify.

``--full-state`` keeps the legacy behavior — full wire blobs both ways
(still version-tagged frames, still digest-verified) — as the A/B
comparator: at the default 5% divergence the delta session ships a
fraction of the full-state bytes.

Run it:

    python examples/replicate_tcp.py                    # delta sync demo
    python examples/replicate_tcp.py --full-state       # legacy full state
    python examples/replicate_tcp.py --objects 1000 --divergence 0.01
    python examples/replicate_tcp.py --gossip 5         # N-peer fleet mode
    python examples/replicate_tcp.py --window 16        # windowed ARQ session
    python examples/replicate_tcp.py --gossip 3 --window 0   # stop-and-wait

``--window N`` runs the session over the hardened windowed transport
(``crdt_tpu.cluster.ResilientTransport``): seq-numbered CRC-guarded
envelopes with up to N DATA frames in flight, selective acks, and (at
N >= 2 on both peers) the v4 streaming delta/descent protocol.  ``0``
pins a 1-frame window — stop-and-wait — as the A/B control; at
convergence the peers print frames-in-flight high-water, retransmit
counts and the descent round-trip count, and ``--gossip`` mode prints a
fleet digest fingerprint so a windowed fleet can be asserted
byte-identical to a stop-and-wait control fleet.

``--gossip N`` runs the cluster runtime instead of a single session: N
replicas (in-process nodes over real loopback TCP sockets), each with a
listener, a peer roster (``crdt_tpu.cluster.Membership``) and a
staleness-driven ``GossipScheduler``, reconcile through hardened
``ResilientTransport`` links until every node's digest vector is
byte-identical (PERF.md "Cluster runtime").

``--metrics-port N`` starts the live observability exporter
(:mod:`crdt_tpu.obs`) in the peer process: ``GET /metrics`` is the
Prometheus view of the ``wire.sync.*`` counters and phase latency
histograms, ``GET /events`` is the flight recorder (filter to this
session with ``?session=<id>`` — the peer prints its session ID), and
``GET /healthz`` is the liveness probe.  ``--linger S`` keeps the
exporter up for up to S seconds after the sync finishes (returning as
soon as both ``/metrics`` and ``/events`` have been scraped after the
sync finished — scrapes that raced the sync don't count), so a
scraper — PERF.md's ``curl`` walkthrough, or the automated test — can
read the final state before the process exits.

(`--platform cpu` forces the CPU backend, e.g. when no TPU is
reachable; the kernels are platform-agnostic.)
"""

from __future__ import annotations

import argparse
import os
import socket
import struct
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _send_frame(sock: socket.socket, frame: bytes) -> None:
    sock.sendall(struct.pack("<I", len(frame)))
    sock.sendall(frame)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> bytes:
    (ln,) = struct.unpack("<I", _recv_exact(sock, 4))
    return _recv_exact(sock, ln)


def _build_fleet(n_objects: int, actor: int, divergence: float, seed: int):
    """N scalar Orswots: a shared base history (seed-deterministic,
    actor 0) + this peer's own ops on a ``divergence`` fraction of
    objects.  Both peers call this with the SAME ``seed`` and different
    ``actor``, so they agree everywhere except the divergent rows."""
    import numpy as np

    from crdt_tpu import Orswot

    rng = np.random.RandomState(seed)
    fleet = []
    for i in range(n_objects):
        o = Orswot()
        for _ in range(int(rng.randint(1, 5))):
            member = int(rng.randint(0, 64))
            o.apply(o.add(member, o.value().derive_add_ctx(0)))
        if i % 7 == 0:  # a causal remove on some objects
            read = o.value()
            if read.val:
                m = sorted(read.val)[0]
                o.apply(o.remove(m, o.contains(m).derive_rm_ctx()))
        fleet.append(o)
    # divergent tail: per-peer ops the OTHER replica has not seen (the
    # rng is past the shared prefix here, so draws differ per peer only
    # through the actor-dependent op content below)
    n_div = int(n_objects * divergence)
    div_rng = np.random.RandomState(seed + 1)
    targets = div_rng.choice(n_objects, size=n_div, replace=False)
    for i in targets:
        o = fleet[int(i)]
        member = int(100 + actor * 10 + int(i) % 7)
        o.apply(o.add(member, o.value().derive_add_ctx(actor)))
    return fleet


def peer(role: str, port: int, n_objects: int, platform: str | None,
         full_state: bool = False, divergence: float = 0.05,
         metrics_port: int | None = None, linger_s: float = 0.0,
         window: int | None = None) -> str:
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)

    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.sync import SyncSession
    from crdt_tpu.utils.interning import Universe

    metrics_server = None
    if metrics_port is not None:
        from crdt_tpu.obs import export as obs_export
        from crdt_tpu.utils import tracing

        # enable spans so sync phase latencies land in the histograms
        # the exporter serves (counters/events are always-on anyway)
        tracing.enable(True)
        metrics_server = obs_export.start_metrics_server(port=metrics_port)
        print(
            f"{role}: metrics exporter on "
            f"http://127.0.0.1:{metrics_server.port}/metrics",
            flush=True,
        )

    # identity universe: int actors/members -> the native C++ bulk codec
    # parses/serializes the blobs with zero host-side interning state
    uni = Universe.identity(CrdtConfig(num_actors=8, member_capacity=32,
                                       deferred_capacity=8, counter_bits=32))
    actor = 1 if role == "server" else 2
    mine = OrswotBatch.from_scalar(
        _build_fleet(n_objects, actor, divergence, seed=42), uni
    )

    if role == "server":
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", port))
        srv.listen(1)
        srv.settimeout(120)  # a peer that never comes must not orphan us
        sock, _ = srv.accept()
        srv.close()
    else:
        # the peers race at startup: retry until the server's bind lands
        import time

        deadline = time.monotonic() + 120
        while True:
            try:
                sock = socket.create_connection(("127.0.0.1", port), timeout=10)
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.5)

    other = "client" if role == "server" else "server"
    # full-state reference size (one serialization pass, outside the
    # timed sync): feeds the per-peer delta_ratio gauge the exporter
    # serves — what this sync cost vs shipping everything
    full_ref = sum(len(b) for b in mine.to_wire(uni))
    session = SyncSession(mine, uni, full_state=full_state, peer=other,
                          full_state_bytes=full_ref)
    transport = None
    with sock:
        if window is None:
            # legacy raw length-prefixed framing, no ARQ envelope
            report = session.sync(
                lambda frame: _send_frame(sock, frame),
                lambda: _recv_frame(sock),
            )
        else:
            # the hardened windowed transport: frames ride seq-numbered
            # CRC-guarded envelopes with up to `window` in flight
            # (window 0 = stop-and-wait = a 1-frame window); both peers
            # must run with --window for the envelopes to parse
            import dataclasses

            from crdt_tpu.cluster import (
                ResilientTransport, RetryPolicy, TcpTransport,
            )

            policy = dataclasses.replace(RetryPolicy(),
                                         window=max(1, window))
            transport = ResilientTransport(
                TcpTransport(sock, default_timeout=60.0), policy,
                name=role,
            )
            try:
                report = session.sync(transport)
            finally:
                transport.close()  # drains the window of stragglers

    status = "CONVERGED" if report.converged else "DIVERGED"
    mode = "full-state" if full_state else "delta"
    print(
        f"{role}: {n_objects} objects  mode={mode}  "
        f"session={session.session_id}  trace={report.trace_id}  "
        f"diverged={report.diverged}  delta_objects={report.delta_objects_sent}  "
        f"sent: digest={report.digest_bytes_sent}B delta="
        f"{report.delta_bytes_sent}B full={report.full_bytes_sent}B  {status}",
        flush=True,
    )
    if transport is not None:
        print(
            f"{role}: transport window={report.window} "
            f"streaming={report.streaming}  "
            f"inflight_hw={transport.window_hw}  "
            f"retransmits={transport.retransmits}  "
            f"sacks={transport.sacks_sent}  "
            f"delta_chunks={report.delta_chunks_sent}  "
            f"descent_rtts={report.tree_round_trips}",
            flush=True,
        )
    if metrics_server is not None and linger_s > 0:
        # hold the exporter up until someone has read the FINAL state
        # (or the linger budget runs out) — a sync finishing in
        # milliseconds must not close the scrape window with it, and a
        # scrape that raced the sync itself read a half-told story, so
        # only scrapes arriving from here on count
        import time

        baseline = metrics_server.scrape_counts()
        deadline = time.monotonic() + linger_s
        while time.monotonic() < deadline:
            if metrics_server.scraped("/metrics", "/events",
                                      since=baseline):
                break
            time.sleep(0.05)
    if metrics_server is not None:
        metrics_server.stop()
    return status


def mesh_demo(shards: int, n_objects: int, platform: str | None,
              divergence: float = 0.05, zipf_s: float = 1.1) -> int:
    """``--mesh S``: one logical replica sharded over an S-device
    object mesh (``crdt_tpu.mesh``), demonstrated on forced host
    devices.  Drives a Zipf-skewed write history through the heat
    observatory, lets the placement planner pick the subtree granule
    (the ``plan=mesh:S`` score), runs the whole anti-entropy round as
    ONE pjit'd step, and prints per-shard planner-predicted vs
    measured load plus the digest parity against the unsharded
    control."""
    # the mesh ladder needs 8 visible devices; force them BEFORE the
    # first jax import (a no-op on a real multi-device backend)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    if platform:
        os.environ["JAX_PLATFORMS"] = platform

    import numpy as np

    from crdt_tpu import mesh as mesh_mod
    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.obs import heat as heat_mod
    from crdt_tpu.obs import stability as stability_mod
    from crdt_tpu.sync import digest as digest_mod
    from crdt_tpu.utils.interning import Universe

    uni = Universe.identity(CrdtConfig(num_actors=8, member_capacity=32,
                                       deferred_capacity=8,
                                       counter_bits=32))
    a = OrswotBatch.from_scalar(
        _build_fleet(n_objects, actor=1, divergence=divergence, seed=17),
        uni)
    b = OrswotBatch.from_scalar(
        _build_fleet(n_objects, actor=2, divergence=divergence, seed=17),
        uni)

    # a Zipf-skewed write history feeds the heat observatory — the
    # planner prices shard boundaries against THIS, not a uniform guess
    _subtrees, span = stability_mod.subtree_layout(n_objects)
    trk = heat_mod.HeatTracker()
    rng = np.random.RandomState(7)
    ranks = np.arange(1, n_objects + 1, dtype=np.float64)
    probs = ranks ** -max(zipf_s, 1e-9)
    probs /= probs.sum()
    writes = rng.choice(n_objects, size=4096, p=probs)
    trk.record_writes(writes, n_objects)
    heat = trk.heat_vector()

    layout = mesh_mod.choose_layout(n_objects, shards, heat=heat,
                                    span=span)
    predicted = heat_mod.score_plan(f"mesh:{shards}", heat, n=n_objects,
                                    span=span, granule=layout.granule)
    print(f"mesh: {shards} shards over {n_objects} objects, planner "
          f"granule {layout.granule} (predicted imbalance "
          f"{predicted['imbalance']})")

    sa = mesh_mod.ShardedBatch.shard(a, uni, shards=shards, heat=heat,
                                     span=span)
    sb = mesh_mod.ShardedBatch.shard(b, uni, shards=shards, heat=heat,
                                     span=span)
    res = mesh_mod.anti_entropy_step(sa, sb)

    # unsharded control: same merge + digest, no mesh
    control = np.asarray(digest_mod.digest_of(a.merge(b), uni),
                         dtype=np.uint64)
    parity = bool(np.array_equal(res.digests, control))

    # measured load: the heat vector AFTER attributing the rows that
    # actually churned this round (the diverged digests) as repair heat
    pre = digest_mod.digest_of(a, uni)
    post = digest_mod.digest_of(b, uni)
    churned = np.nonzero(np.asarray(pre) != np.asarray(post))[0]
    if churned.size:
        trk.record_repair(churned, n_objects)
    measured = mesh_mod.shard_loads(layout, trk.heat_vector(), span)
    predicted_loads = predicted["loads"]
    print(f"{'shard':>5} {'objects':>8} {'predicted':>10} {'measured':>10}")
    for s, (lo, hi) in enumerate(layout.ranges()):
        print(f"{s:>5} {hi - lo:>8} {predicted_loads[s]:>10.1f} "
              f"{measured[s]:>10.1f}")
    sa.publish_gauges(heat_vector=trk.heat_vector(), span=span)

    print(f"digest parity vs unsharded control: "
          f"{'BYTE-IDENTICAL' if parity else 'DIVERGED'} "
          f"({res.digests.size} lanes, {res.live_members} live members)")
    return 0 if parity else 1


def gossip_demo(n_peers: int, n_objects: int, platform: str | None,
                divergence: float, max_sweeps: int = 20,
                fleet_port: int | None = None, ops_rate: int = 0,
                ops_sweeps: int = 3, reads_rate: int = 0,
                gc_enabled: bool = False,
                gc_interval: int = 1, gc_hysteresis: float = 0.5,
                digest_tree: bool = False, zipf_s: float = 0.0,
                burst_len: int = 1, durable_dir: str | None = None,
                kill_sweep: int = 2, window: int | None = None,
                heat: bool = False) -> int:
    """N in-process replicas over real loopback TCP, reconciled by the
    cluster runtime (``crdt_tpu/cluster``): each node owns a listener
    (accepted sessions run through the same hardened transport stack),
    a peer roster, and a staleness-driven ``GossipScheduler``.  The
    demo drives deterministic scheduler sweeps (round-robin
    ``run_round`` across nodes) until every node's digest vector is
    byte-identical — the same convergence oracle the sessions
    themselves use.

    Every node carries a ``FleetObservatory``, so telemetry snapshots
    piggyback on the gossip sessions; at convergence the demo prints
    ONE merged fleet snapshot (fleet counters = per-node sums) instead
    of N disjoint per-node ``/metrics`` views, plus the shared trace ID
    of the final session (both halves carry it — PERF.md "Fleet
    observability" walks the curl side).  ``--fleet-port`` additionally
    serves the live merged view on ``GET /fleet``.

    ``--ops R`` turns the demo into a LIVE-WRITE run: for the first few
    sweeps, R random user writes per sweep land on random nodes through
    the op-based front-end (``ClusterNode.submit_ops`` — batched
    ``derive_add_ctx`` dots, :mod:`crdt_tpu.oplog`) WHILE gossip is
    reconciling, so anti-entropy and ingest genuinely overlap; once the
    writes stop, the fleet must still converge to byte-identical digest
    vectors — the mixed op+state acceptance shape (PERF.md "Op-based
    replication").

    ``--reads R`` adds the READ half of the client protocol
    (:mod:`crdt_tpu.serve`): R live reads per sweep land on random
    nodes WHILE gossip reconciles — each injection writes a probe
    member through ``submit_writes``, takes the ack floor
    (``write_vv``), and reads it straight back under read-your-writes
    (a violation is an assertion, not a statistic), plus monotonic
    reads whose returned tokens must never regress per node and
    frontier-stable reads tallying per-row stability against the PR 15
    frontier.  At quiescence a final frontier-mode read on every node
    must come back all-rows-stable (PERF.md "Read front-end").

    ``--durable DIR`` arms every node with a :class:`crdt_tpu.durable.
    Durability` manager (WAL-ahead ingest + a checkpoint at every
    gossip round end) and turns the run into the crash-recovery demo:
    at sweep ``kill_sweep`` node n1 is killed — listener closed, object
    dropped, nothing flushed, exactly what kill -9 leaves — and one
    sweep later it restores from its snapshot + WAL
    (:func:`crdt_tpu.durable.recover`), rejoins through NORMAL delta
    sync, and the demo prints the recovery wall, bytes replayed from
    the WAL vs bytes delta-synced during the rejoin, and asserts the
    rejoin shipped zero full-state frames (PERF.md "Durability")."""
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)

    import threading

    import numpy as np

    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.cluster import (
        ClusterNode, GossipScheduler, Membership, ResilientTransport,
        RetryPolicy, TcpTransport, hello_accept, hello_dial,
    )
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.obs.fleet import FleetObservatory
    from crdt_tpu.utils.interning import Universe

    uni = Universe.identity(CrdtConfig(num_actors=max(8, n_peers + 2),
                                       member_capacity=32,
                                       deferred_capacity=8,
                                       counter_bits=32))
    policy = RetryPolicy(send_deadline_s=20.0, recv_deadline_s=20.0,
                         ack_timeout_s=0.25, max_backoff_s=2.0,
                         retry_budget=64)
    if window is not None:
        # --window 0 = stop-and-wait (a 1-frame window); any N >= 2
        # lets sessions pipeline DATA frames and stream v4 descents
        import dataclasses

        policy = dataclasses.replace(policy, window=max(1, window))

    from crdt_tpu.oplog import OpLog

    def make_gc_engine():
        if not gc_enabled:
            return None
        from crdt_tpu.gc import GcEngine, GcPolicy

        return GcEngine(GcPolicy(
            interval_rounds=gc_interval,
            shrink_hysteresis=gc_hysteresis,
        ))

    def make_durability(node_name):
        if durable_dir is None:
            return None
        from crdt_tpu.durable import Durability

        return Durability(os.path.join(durable_dir, node_name),
                          interval_rounds=1, retain=2)

    nodes = []
    for i in range(n_peers):
        fleet = _build_fleet(n_objects, actor=i + 1,
                             divergence=divergence, seed=42)
        batch = OrswotBatch.from_scalar(fleet, uni)
        gc_engine = make_gc_engine()
        if gc_enabled:
            # over-provision the planes as an earlier burst's regrow
            # would have, so the demo has real padding to reclaim
            batch = batch.with_capacity(uni.config.member_capacity * 4,
                                        uni.config.deferred_capacity * 4)
        nodes.append(ClusterNode(
            f"n{i}", batch, uni,
            busy_timeout_s=30.0,
            observatory=FleetObservatory(f"n{i}"),
            # op front-end armed up front so sessions advertise the
            # piggyback capability from the first hello (always armed
            # in durable mode — the WAL rides the op ingest path)
            oplog=OpLog(uni) if (ops_rate or reads_rate or durable_dir)
            else None,
            gc=gc_engine,
            # sync protocol v3: sessions compare digest-tree roots and
            # descend into diverged subtrees instead of shipping the
            # flat O(N) digest vector
            digest_tree=digest_tree,
            durability=make_durability(f"n{i}"),
        ))

    fleet_server = None
    if fleet_port is not None:
        from crdt_tpu.obs import export as obs_export

        fleet_server = obs_export.start_metrics_server(
            port=fleet_port, observatory=nodes[0].observatory
        )
        print(
            f"fleet: merged observatory on "
            f"http://127.0.0.1:{fleet_server.port}/fleet "
            f"(?format=json for per-node slices, ?trace=<id> for a "
            f"stitched session timeline)", flush=True,
        )

    # one listener per node; accepted connections run the acceptor leg
    # through the same ResilientTransport stack the dialers use.  The
    # served node is looked up LATE (nodes[i] at accept time), so a
    # killed slot refuses and a restarted one serves its new object.
    stop = threading.Event()
    servers: list = [None] * n_peers
    ports = {}

    def start_listener(i):
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(n_peers)
        srv.settimeout(0.2)  # poll the stop flag between accepts
        ports[f"n{i}"] = srv.getsockname()[1]
        servers[i] = srv

        def listener():
            while not stop.is_set():
                try:
                    sock, _ = srv.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return

                def serve(sock=sock):
                    node = nodes[i]
                    if node is None:  # killed between accept and serve
                        sock.close()
                        return
                    t = ResilientTransport(
                        TcpTransport(sock, default_timeout=20.0), policy,
                        name=f"{node.node_id}-accept",
                    )
                    try:
                        peer = hello_accept(t, timeout=20.0)
                        node.accept(t, peer_id=peer)
                    except Exception as e:  # a failed inbound session
                        print(f"{node.node_id}: inbound session failed: "
                              f"{type(e).__name__}: {e}", flush=True)
                    finally:
                        t.close()

                threading.Thread(target=serve, daemon=True).start()

        threading.Thread(target=listener, daemon=True,
                         name=f"listen-n{i}").start()

    for i in range(n_peers):
        start_listener(i)

    def make_dialer(node):
        def dial(peer):
            from crdt_tpu.error import PeerUnavailableError

            try:
                sock = socket.create_connection(
                    ("127.0.0.1", ports[peer.peer_id]), timeout=20.0)
            except OSError as e:
                # a killed peer's port refuses: that is a membership
                # fact (alive -> suspect -> dead), not a crash
                raise PeerUnavailableError(
                    f"dial {peer.peer_id} refused: {e}") from e
            t = ResilientTransport(
                TcpTransport(sock, default_timeout=20.0), policy,
                name=f"{node.node_id}->{peer.peer_id}",
            )
            hello_dial(t, node.node_id)
            return t
        return dial

    def make_sched(i):
        membership = Membership(suspect_after=2, dead_after=5)
        for j in range(n_peers):
            if j != i:
                membership.add(f"n{j}", address=ports[f"n{j}"])
        return GossipScheduler(
            nodes[i], membership, make_dialer(nodes[i]), fanout=2,
            session_timeout_s=60.0, seed=i,
        )

    scheds = [make_sched(i) for i in range(n_peers)]

    ops_rng = np.random.RandomState(4242)
    total_ops = 0
    # key-skew / burst knobs (crdt_tpu.utils.workload): Zipfian hot
    # keys cluster divergence into few digest subtrees — the descent's
    # best case — while the default stays uniform (its worst case)
    from crdt_tpu.utils.workload import WorkloadGen

    key_gen = WorkloadGen(n_objects, seed=4242, zipf_s=zipf_s,
                          burst_len=burst_len)

    def inject_writes(r):
        """R random user writes into random nodes, mid-round: each
        write mints its dot through the node's own write front-end
        (``submit_writes`` — batched clone-and-increment against the
        log-inclusive write clock, so a node mid-session can never
        reuse a dot), using a per-node writer actor; folded immediately
        when the node is idle, queued (and piggybacked to the next
        session peer) when it is busy."""
        nonlocal total_ops
        per_node = np.bincount(
            ops_rng.randint(0, n_peers, r), minlength=n_peers)
        for i, cnt in enumerate(per_node):
            if not cnt or nodes[i] is None:  # a killed node takes none
                continue
            nodes[i].submit_writes(
                key_gen.draw(int(cnt)),
                ops_rng.randint(200, 216, cnt).astype(np.int32),
                actor=i + 1,
            )
            total_ops += cnt

    # the read front-end (crdt_tpu.serve): its own key stream (so
    # toggling --reads never perturbs the write keys) and a per-node
    # ServeLoop with a generous park — a gossip session can hold the
    # node's fold lock for a while, and a parked RYW read waiting it
    # out is the designed behaviour, not a failure
    from crdt_tpu.error import ConsistencyUnavailableError
    from crdt_tpu.serve import ST_OK, ReadRequest, ServeLoop

    read_rng = np.random.RandomState(2424)
    read_gen = WorkloadGen(n_objects, seed=2424, zipf_s=zipf_s)
    serve_loops: dict = {}  # keyed by node OBJECT: restarts get fresh
    mono_tokens: dict = {}
    read_stats = {"reads": 0, "ryw": 0, "ryw_parked_out": 0, "mono": 0,
                  "frontier_ok": 0, "frontier_not_stable": 0,
                  "frontier_unformed": 0}

    def serve_on(i, req):
        node = nodes[i]
        loop = serve_loops.get(node)
        if loop is None:
            loop = ServeLoop(node, park_timeout_s=5.0)
            serve_loops[node] = loop
        return loop.serve(req)

    def inject_reads(r):
        """R live reads into random nodes, mid-round, one batch per
        mode.  Read-your-writes is probed end-to-end: write a marker
        member through the node's own front-end, take the ack floor
        (``write_vv`` — batch clock + everything parked in the log),
        and require the read to be admitted at or above it; an admitted
        read that misses the member is a protocol violation and dies
        loudly right here."""
        nonlocal total_ops
        per_node = np.bincount(
            read_rng.randint(0, n_peers, r), minlength=n_peers)
        for i, cnt in enumerate(per_node):
            node = nodes[i]
            if not cnt or node is None:
                continue
            # (1) read-your-writes on a fresh acknowledged write
            key = read_gen.draw(1)
            member = np.array([180 + i], np.int32)
            node.submit_writes(key, member, actor=i + 1)
            total_ops += 1
            ack = node.write_vv()
            try:
                frame = serve_on(i, ReadRequest.reads(
                    key, member=member, mode="ryw", require=ack))
                assert int(frame.val[0]) == 1, (
                    f"{node.node_id}: read-your-writes VIOLATED — "
                    f"admitted ryw read of obj {int(key[0])} does not "
                    f"see acknowledged member {int(member[0])}"
                )
                read_stats["ryw"] += 1
            except ConsistencyUnavailableError:
                # parked past the timeout behind a long fold lock —
                # loud and typed, never a silent stale read
                read_stats["ryw_parked_out"] += 1
            # (2) monotonic reads: the returned token may never regress
            keys = read_gen.draw(int(cnt))
            tok = mono_tokens.get(node)
            frame = serve_on(i, ReadRequest.reads(
                keys, mode="monotonic", require=tok))
            if tok is not None:
                assert np.all(frame.token >= tok), (
                    f"{node.node_id}: monotonic token REGRESSED "
                    f"{tok.tolist()} -> {frame.token.tolist()}"
                )
            mono_tokens[node] = frame.token
            read_stats["mono"] += int(cnt)
            # (3) frontier-stable reads: tally per-row stability
            try:
                frame = serve_on(i, ReadRequest.reads(
                    read_gen.draw(int(cnt)), mode="frontier"))
                ok = int((frame.status == ST_OK).sum())
                read_stats["frontier_ok"] += ok
                read_stats["frontier_not_stable"] += len(frame) - ok
            except ConsistencyUnavailableError:
                read_stats["frontier_unformed"] += int(cnt)
            read_stats["reads"] += 1 + 2 * int(cnt)

    victim = 1 if (durable_dir is not None and n_peers >= 2) else None
    killed_at = None
    rejoin_baseline = None
    recovery = None

    def kill_victim(sweep):
        """kill -9 in-process: close the listener, drop the object —
        no drain, no flush, no goodbye.  Everything the node will have
        after this moment is what its Durability manager already put
        on disk."""
        servers[victim].close()
        nodes[victim] = None
        scheds[victim] = None
        print(f"kill: n{victim} killed -9 at sweep {sweep} "
              "(listener closed, in-memory state dropped)", flush=True)

    def restart_victim():
        nonlocal rejoin_baseline, recovery
        from crdt_tpu.durable import recover
        from crdt_tpu.obs.stability import StabilityTracker
        from crdt_tpu.utils import tracing as _tracing

        c = _tracing.counters()
        rejoin_baseline = {
            "full_frames": c.get("sync.full_state_fallback", 0),
            "full_bytes": c.get("wire.sync.full.bytes", 0),
            "delta_bytes": c.get("wire.sync.delta.bytes", 0),
        }
        recovery = recover(os.path.join(durable_dir, f"n{victim}"))
        gc_engine = make_gc_engine()
        if gc_engine is not None and recovery.watermark is not None:
            # resume GC's stability frontier from the persisted clock
            gc_engine.restore_watermark(recovery.watermark)
        # the convergence observatory's frontier resumes the same way:
        # the persisted fleet-min clock is a monotone floor, so the
        # rejoined observer's published frontier never regresses
        stability = StabilityTracker()
        if recovery.frontier is not None:
            stability.restore(recovery.frontier)
        nodes[victim] = ClusterNode(
            f"n{victim}", recovery.batch, recovery.universe,
            busy_timeout_s=30.0,
            observatory=FleetObservatory(f"n{victim}"),
            oplog=OpLog(recovery.universe),
            applier=recovery.applier,
            gc=gc_engine,
            digest_tree=digest_tree,
            durability=make_durability(f"n{victim}"),
            stability_tracker=stability,
        )
        start_listener(victim)
        scheds[victim] = make_sched(victim)
        rep = recovery.report
        print(f"recovery: n{victim} restored generation "
              f"{rep.generation} in {rep.wall_s * 1e3:.1f}ms — "
              f"replayed {rep.replayed_frames} WAL frames / "
              f"{rep.replayed_ops} ops ({rep.replayed_bytes}B), "
              f"{rep.parked_ops} re-parked; rejoining via delta sync",
              flush=True)

    def roster_for(i):
        return [f"n{j}" for j in range(n_peers) if j != i]

    def fleet_vv_min(live):
        """Element-wise min over the live nodes' version vectors — what
        the stability frontier must equal once the fleet quiesced AND
        every observer re-converged with every peer."""
        from crdt_tpu.sync import digest as digest_mod

        vvs = [np.asarray(digest_mod.version_vector(n.batch), np.uint64)
               for n in live]
        width = max(v.size for v in vvs)
        out = None
        for v in vvs:
            if v.size < width:
                v = np.concatenate(
                    [v, np.zeros(width - v.size, np.uint64)])
            out = v if out is None else np.minimum(out, v)
        return out

    def frontier_settled(live):
        """Every live node's published fleet-min frontier clock equals
        the fleet VV min — needs each observer to have converged with
        each peer AFTER the last write, which the staleness-ranked
        scheduler reaches within a few post-quiescence sweeps."""
        target = fleet_vv_min(live)
        for n in live:
            rep = n.stability.frontier(
                n.batch, peers=roster_for(int(n.node_id[1:])))
            if rep is None:
                return False
            clock = np.asarray(rep.clock, np.uint64)
            w = max(clock.size, target.size)
            c = np.concatenate([clock, np.zeros(w - clock.size, np.uint64)])
            t = np.concatenate([target,
                                np.zeros(w - target.size, np.uint64)])
            if not np.array_equal(c, t):
                return False
        return True

    sweeps = 0
    converged = False
    settled = False
    try:
        for sweeps in range(1, max_sweeps + 1):
            if victim is not None and killed_at is None \
                    and sweeps == kill_sweep:
                kill_victim(sweeps)
                killed_at = sweeps
            elif killed_at is not None and nodes[victim] is None \
                    and sweeps == killed_at + 1:
                restart_victim()
            writing = ops_rate and sweeps <= ops_sweeps
            reading = reads_rate and sweeps <= ops_sweeps
            if writing:
                inject_writes(ops_rate)
            if reading:
                inject_reads(reads_rate)
            for sched in scheds:
                if sched is None:
                    continue  # the victim is down this sweep
                if writing:
                    # writes land between (and during) rounds, not just
                    # at sweep boundaries — the live-traffic shape
                    inject_writes(max(1, ops_rate // n_peers))
                if reading:
                    # reads interleave with the gossip rounds too, so
                    # admission races real fold-lock contention
                    inject_reads(max(1, reads_rate // n_peers))
                sched.run_round()
            live = [n for n in nodes if n is not None]
            digests = [n.digest() for n in live]
            converged = len(live) == n_peers and all(
                np.array_equal(digests[0], d) for d in digests[1:]
            )
            state = ("digest vectors identical" if converged
                     else "still diverged"
                     if len(live) == n_peers else
                     f"{n_peers - len(live)} node(s) down")
            if ops_rate:
                state += f" (ops submitted so far: {total_ops})"
            print(f"sweep {sweeps}: {state}", flush=True)
            # while writes flow, convergence is a moving target — only
            # the post-write sweeps decide the verdict; the stability
            # frontier additionally has to SETTLE (every observer
            # re-converged with every peer), so the final state's
            # frontier == fleet-VV-min identity below is assertable
            if converged and not writing and not reading:
                settled = frontier_settled(live)
                if settled:
                    break
    finally:
        stop.set()
        for srv in servers:
            if srv is not None:
                srv.close()

    if recovery is not None:
        from crdt_tpu.utils import tracing as _tracing

        c = _tracing.counters()
        full_frames = c.get("sync.full_state_fallback", 0) \
            - rejoin_baseline["full_frames"]
        delta_bytes = c.get("wire.sync.delta.bytes", 0) \
            - rejoin_baseline["delta_bytes"]
        print(
            f"rejoin: {recovery.report.replayed_bytes}B replayed from "
            f"the WAL vs {delta_bytes}B delta-synced fleet-wide during "
            f"the rejoin; full-state fallbacks={full_frames}",
            flush=True,
        )
        assert full_frames == 0, \
            "rejoin shipped a full-state frame (must be delta-only)"

    if ops_rate:
        print(f"ops: {total_ops} live writes ingested through "
              f"submit_ops while gossip ran; fleet "
              f"{'CONVERGED' if converged else 'DIVERGED'} after writes "
              "stopped", flush=True)
        assert not converged or all(
            len(n._oplog) == 0 for n in nodes if n._oplog is not None
        ), "converged with undrained op logs"

    if reads_rate:
        print(
            f"reads: {read_stats['reads']} live reads served while "
            f"gossip ran — ryw probes {read_stats['ryw']} "
            f"(0 violations; {read_stats['ryw_parked_out']} parked out "
            f"behind the fold lock), monotonic {read_stats['mono']} "
            f"(0 token regressions), frontier-stable rows "
            f"{read_stats['frontier_ok']} ok / "
            f"{read_stats['frontier_not_stable']} not-yet-stable / "
            f"{read_stats['frontier_unformed']} before a frontier "
            "formed", flush=True,
        )
        assert read_stats["ryw"] > 0, \
            "--reads ran but no read-your-writes probe was admitted"
        if converged and settled:
            # at quiescence the frontier IS the fleet VV min, so a
            # frontier-mode read of ANY row must come back stable
            for i, node in enumerate(nodes):
                if node is None:
                    continue
                frame = serve_on(i, ReadRequest.reads(
                    np.arange(min(64, n_objects)), mode="frontier"))
                assert bool((frame.status == ST_OK).all()), (
                    f"{node.node_id}: rows still not-stable under a "
                    "settled frontier"
                )
            print("reads: quiescent frontier-mode sweep all-rows-stable "
                  "on every node", flush=True)

    # ONE merged fleet snapshot (every node's slice reached node 0 on
    # the gossip itself — no scraper, no federation) instead of N
    # disjoint per-node /metrics views
    merged = nodes[0].observatory.merged()
    fc = merged.fleet_counters()
    sessions_by_node = merged.counters_by_node("sync.sessions")
    print(f"fleet: merged snapshot spans nodes={merged.nodes()}", flush=True)
    print(
        f"fleet: sync.sessions={fc.get('sync.sessions', 0)} "
        f"(per-node {sessions_by_node}; fleet counter == sum of "
        f"per-node values by G-Counter merge)", flush=True,
    )
    trace = next(
        (n.last_report.trace_id for n in reversed(nodes)
         if n.last_report is not None), None,
    )
    print(f"fleet: final session trace={trace} "
          f"(both peers' /events carry it)", flush=True)

    # the latency observatory's read of the run: the last session's
    # critical-path split, the per-link SRTT the transports measured,
    # and (on --ops runs) the write-to-visible lag each observer saw
    last = next((n.last_report for n in reversed(nodes)
                 if n is not None and n.last_report is not None), None)
    if last is not None and last.profile is not None:
        p = last.profile
        print(
            f"latency: last session wall {p.wall_ns / 1e6:.1f}ms = "
            f"serialize {p.serialize_ns / 1e6:.1f} + network "
            f"{p.network_ns / 1e6:.1f} + kernel {p.kernel_ns / 1e6:.1f} "
            f"+ other {p.other_ns / 1e6:.1f} + unaccounted "
            f"{p.unaccounted_ns / 1e6:.1f} "
            f"(network_wait {p.network_wait_frac:.0%})", flush=True,
        )
    from crdt_tpu.obs import metrics as _obs_metrics

    _gauges = _obs_metrics.registry().snapshot()["gauges"]
    srtts = {k.split(".")[2]: v for k, v in _gauges.items()
             if k.startswith("cluster.transport.") and
             k.endswith(".rtt_srtt_s")}
    if srtts:
        worst = max(srtts, key=srtts.get)
        print(f"latency: srtt over {len(srtts)} link(s), worst "
              f"{worst}={srtts[worst] * 1e3:.1f}ms", flush=True)
    if ops_rate:
        for node in nodes:
            if node is None:
                continue
            node.lag_tracker.refresh()
            lag = node.lag_tracker.snapshot()
            for origin, st in sorted(lag["peers"].items()):
                print(
                    f"latency: {node.node_id} sees {origin} "
                    f"write-to-visible p50={st['p50_s'] * 1e3:.1f}ms "
                    f"p99={st['p99_s'] * 1e3:.1f}ms "
                    f"({st['samples']} samples, "
                    f"{st['outstanding']} outstanding)", flush=True,
                )

    # the convergence observatory's read of the run: the fleet
    # stability frontier (the clock the future truncate-epoch proposer
    # consumes), how old the worst divergence got, and the lattice
    # auditor's verdict.  At quiescence, with every observer settled,
    # the frontier IS the fleet VV min — asserted, not just printed.
    live = [n for n in nodes if n is not None]
    if converged and live:
        target = fleet_vv_min(live)
        worst_age = 0.0
        checks = violations = 0
        for node in live:
            rep = node.stability.frontier(
                node.batch, peers=roster_for(int(node.node_id[1:])))
            assert rep is not None, "frontier unavailable on a clocked fleet"
            assert np.array_equal(
                np.asarray(rep.clock, np.uint64), target), (
                f"{node.node_id}: frontier {rep.clock.tolist()} != "
                f"fleet VV min {target.tolist()} at quiescence"
            )
            snap = node.stability.snapshot()
            worst_age = max(worst_age, snap["aging"]["resolved_age_max_s"]
                            or 0.0)
            checks += snap["audit"]["checks"]
            violations += snap["audit"]["violations"]
        print(
            f"stability: frontier == fleet VV min "
            f"(max_counter={int(target.max(initial=0))}, "
            f"{live[0].stability.snapshot()['frontier']['subtrees']} "
            f"subtree(s)); oldest divergence age "
            f"{max(n.stability.oldest_divergence_age_s() for n in live) * 1e3:.1f}ms "
            f"outstanding / {worst_age * 1e3:.1f}ms worst resolved; "
            f"audit checks={checks} violations={violations}", flush=True,
        )
        assert violations == 0, \
            "lattice auditor recorded violations on a healthy run"

    if heat and live:
        # the heat observatory's read of the run: every node carries a
        # private HeatTracker fed by its own serve loop (reads), op
        # drain (writes), and sync sessions (repair); here the per-node
        # views are joined host-side — the same reduction /fleet serves
        from crdt_tpu.obs import heat as heat_mod

        for node in live:
            node.heat.publish()
        vecs = [node.heat.heat_vector() for node in live]
        width = max((v.size for v in vecs), default=0)
        fleet_heat = np.zeros(max(width, 1), np.float64)
        for v in vecs:
            fleet_heat[:v.size] += v
        merged_hot = heat_mod.merge_hot([node.heat.hot(16) for node in live])
        layout = live[0].heat.snapshot()["layout"]
        rows = {cls: sum(n.heat.snapshot()["rows"][cls] for n in live)
                for cls in heat_mod.CLASSES}
        print(
            f"heat: {int(fleet_heat.sum())} attributed rows across "
            f"{width} subtree(s) (span={layout['span']}) — "
            f"reads={rows['reads']} writes={rows['writes']} "
            f"repair={rows['repair']}", flush=True)
        if merged_hot:
            top = ", ".join(f"#{h['obj']}x{h['count']}"
                            for h in merged_hot[:8])
            print(f"heat: top-k (fleet-merged, +-err<="
                  f"{max(h['err'] for h in merged_hot)}): {top}",
                  flush=True)
        for spec in (f"mesh:{n_peers}", f"ring:{n_peers},k=2"):
            rep = heat_mod.score_plan(
                spec, fleet_heat, n=n_objects, span=layout["span"])
            if rep["kind"] == "mesh":
                print(f"heat: plan {spec}: imbalance="
                      f"{rep['imbalance']} (max={rep['max_load']} "
                      f"mean={rep['mean_load']})", flush=True)
            else:
                print(f"heat: plan {spec}: skew={rep['skew']} "
                      f"movement_frac={rep['movement_frac']}",
                      flush=True)
        if zipf_s and len(merged_hot) >= heat_mod.MIN_FIT_RANKS:
            s_hat, r2 = heat_mod.zipf_fit(
                [h["count"] - h["err"]
                 for h in merged_hot[:heat_mod.ZIPF_FIT_RANKS]])
            if s_hat is not None and rows["writes"] >= 2_000:
                print(f"heat: zipf s_hat={s_hat:.3f} (r2={r2:.3f}) vs "
                      f"driver s={zipf_s}", flush=True)
                # loose bar: the demo's write volume is tiny next to
                # the bench's, and repair heat rides the same sketch
                assert abs(s_hat - zipf_s) <= 0.4, (
                    f"sketch-fitted Zipf exponent {s_hat:.3f} far from "
                    f"the driver's {zipf_s}")
            elif s_hat is not None:
                print(f"heat: zipf s_hat={s_hat:.3f} (r2={r2:.3f}; "
                      f"too few writes to assert)", flush=True)

    if gc_enabled:
        # per-node reclamation story + the watermark clock GC last
        # collected under (the element-wise min over every peer's
        # version vector — counters at or below it are fleet-stable)
        for node in nodes:
            rep = node.last_gc_report
            wm = "never-ran" if rep is None or rep.watermark is None \
                else rep.watermark.clock.tolist()
            print(
                f"gc: {node.node_id} reclaimed="
                f"{node.gc.total_reclaimed_bytes}B over {node.gc.runs} "
                f"pass(es)  member_capacity="
                f"{node.batch.member_capacity}  watermark={wm}",
                flush=True,
            )
    if fleet_server is not None:
        fleet_server.stop()

    if digest_tree:
        from crdt_tpu.utils import tracing as _tracing

        c = _tracing.counters()
        print(
            f"tree: descents={c.get('sync.tree.descents', 0)} "
            f"cutover={c.get('sync.tree.cutover', 0)} "
            f"fallbacks="
            f"{sum(v for k, v in c.items() if k.startswith('sync.tree.fallback.'))} "
            f"digest_cache_hits={c.get('sync.digest.cache.hit', 0)} "
            f"(wire.sync.tree.bytes={c.get('wire.sync.tree.bytes', 0)} vs "
            f"flat wire.sync.digest.bytes="
            f"{c.get('wire.sync.digest.bytes', 0)})", flush=True,
        )

    # the windowed-ARQ story of the run: fleet-wide recovery tallies,
    # the deepest any link pipelined, and the last session's descent
    # round-trip count — the numbers PERF.md "Windowed transport" tracks
    from crdt_tpu.utils import tracing as _tracing

    c = _tracing.counters()
    hw = max(
        [int(v) for k, v in _gauges.items()
         if k.startswith("cluster.transport.")
         and k.endswith(".window_inflight_hw")] or [0],
    )
    print(
        f"transport: window={policy.window}  inflight_hw={hw}  "
        f"retransmits={c.get('cluster.transport.retransmits', 0)}  "
        f"frames_sacked={c.get('cluster.transport.window.sacked', 0)}  "
        f"window_fallbacks={c.get('cluster.transport.fallback.window', 0)}  "
        f"descent_rtts="
        f"{last.tree_round_trips if last is not None else 0}  "
        f"streaming_last={last.streaming if last is not None else False}",
        flush=True,
    )

    if converged and live:
        # a transport-independent fingerprint of the converged state,
        # so an A/B harness can assert a windowed fleet landed on the
        # byte-identical lattice point a stop-and-wait fleet did
        import hashlib

        sha = hashlib.sha256(live[0].digest().tobytes()).hexdigest()[:16]
        print(f"gossip: fleet digest sha256={sha}", flush=True)

    verdict = "CONVERGED" if converged else "DIVERGED"
    print(f"gossip: {n_peers} peers x {n_objects} objects  "
          f"sweeps={sweeps}  {verdict}", flush=True)
    return 0 if converged else 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("role", nargs="?", default="demo",
                    choices=["demo", "server", "client"])
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--objects", type=int, default=64)
    ap.add_argument("--divergence", type=float, default=0.05,
                    help="fraction of objects with peer-local ops")
    ap.add_argument("--full-state", action="store_true",
                    help="legacy behavior: ship full state instead of "
                         "digest-driven deltas")
    ap.add_argument("--platform", default=None,
                    help="force a JAX platform (e.g. cpu)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve /metrics, /events, /healthz on this port "
                         "(crdt_tpu.obs exporter; server/client roles only)")
    ap.add_argument("--linger", type=float, default=0.0,
                    help="with --metrics-port: keep the exporter alive up "
                         "to this many seconds after the sync (returns as "
                         "soon as /metrics and /events were both scraped)")
    ap.add_argument("--gossip", type=int, default=0, metavar="N",
                    help="N-peer gossip mode: N in-process replicas over "
                         "loopback TCP reconciled by the cluster runtime "
                         "(crdt_tpu.cluster) until their digest vectors "
                         "are byte-identical")
    ap.add_argument("--fleet-port", type=int, default=None,
                    help="with --gossip: serve the live CRDT-merged fleet "
                         "snapshot on GET /fleet at this port (0 picks a "
                         "free one); the demo prints the merged snapshot "
                         "at convergence either way")
    ap.add_argument("--ops", type=int, default=0, metavar="R",
                    help="with --gossip: drive R random user writes per "
                         "sweep into random nodes through the op-based "
                         "front-end (crdt_tpu.oplog / submit_ops) WHILE "
                         "gossip runs, then assert the fleet still "
                         "converges after writes stop")
    ap.add_argument("--reads", type=int, default=0, metavar="R",
                    help="with --gossip: drive R live reads per sweep "
                         "into random nodes through the batched read "
                         "front-end (crdt_tpu.serve) WHILE gossip runs "
                         "— read-your-writes asserted for every "
                         "acknowledged probe write, monotonic tokens "
                         "asserted never to regress, frontier-stable "
                         "rows tallied against the stability frontier")
    ap.add_argument("--gc", action="store_true",
                    help="with --gossip: enable causal GC (crdt_tpu.gc) — "
                         "each node starts with burst-over-provisioned "
                         "planes, the scheduler settles tombstones and "
                         "re-packs capacity between sessions, and the "
                         "demo prints per-node reclaimed bytes + the "
                         "fleet low-watermark clock at convergence")
    ap.add_argument("--gc-interval", type=int, default=1, metavar="N",
                    help="with --gc: collect every Nth gossip round "
                         "(GcPolicy.interval_rounds; default 1)")
    ap.add_argument("--digest-tree", action="store_true",
                    help="with --gossip: sync protocol v3 — sessions "
                         "compare k-ary digest-tree roots and descend "
                         "into diverged subtrees (O(log N) digest "
                         "frames) instead of shipping the flat O(N) "
                         "digest vector")
    ap.add_argument("--zipf", type=float, default=0.0, metavar="S",
                    help="with --ops: Zipf key-skew exponent for the "
                         "write driver (0 = uniform; ~1.2 = hot keys "
                         "clustered into few digest subtrees)")
    ap.add_argument("--burst", type=int, default=1, metavar="B",
                    help="with --ops: each drawn key repeats for B "
                         "consecutive writes (bursty sessions)")
    ap.add_argument("--heat", action="store_true",
                    help="with --gossip: print the heat observatory's "
                         "read of the run at convergence — fleet-merged "
                         "top-k hot objects, per-subtree read/write/"
                         "repair split, and scored mesh:N + ring:N,k=2 "
                         "placement plans (with --zipf: asserts the "
                         "sketch's fitted exponent against the driver's)")
    ap.add_argument("--durable", default=None, metavar="DIR",
                    help="with --gossip: arm every node with a durable "
                         "snapshot store + op-log WAL under DIR/n<i> "
                         "(crdt_tpu.durable), kill node n1 -9 mid-run, "
                         "restore it from disk, and print recovery "
                         "wall + bytes replayed vs bytes delta-synced "
                         "during the rejoin")
    ap.add_argument("--kill-sweep", type=int, default=2, metavar="K",
                    help="with --durable: kill n1 at sweep K and "
                         "restart it one sweep later (default 2)")
    ap.add_argument("--window", type=int, default=None, metavar="N",
                    help="ARQ window: run the session over the hardened "
                         "windowed transport with up to N frames in "
                         "flight (0 = stop-and-wait). Single-session "
                         "roles print frames-in-flight high-water, "
                         "retransmit and descent round-trip counts; "
                         "--gossip mode sets the fleet's transport "
                         "window and prints the fleet-wide tallies plus "
                         "a digest fingerprint at convergence")
    ap.add_argument("--mesh", type=int, default=0, metavar="S",
                    help="mesh-sharded fleet demo: shard ONE logical "
                         "replica over an S-device object mesh "
                         "(crdt_tpu.mesh; S in {1,2,4,8}, forced host "
                         "devices), run the whole anti-entropy round "
                         "as one pjit'd step, and print per-shard "
                         "planner-predicted vs measured load plus "
                         "digest parity against the unsharded control")
    ap.add_argument("--gc-hysteresis", type=float, default=0.5,
                    help="with --gc: shrink only when the fitted "
                         "capacity rung is at most this fraction of the "
                         "current one (GcPolicy.shrink_hysteresis; "
                         "default 0.5)")
    args = ap.parse_args()

    if args.mesh:
        if args.mesh not in (1, 2, 4, 8):
            ap.error("--mesh needs S in {1, 2, 4, 8}")
        zipf = args.zipf if args.zipf > 0 else 1.1
        return mesh_demo(args.mesh, args.objects, args.platform,
                         divergence=args.divergence, zipf_s=zipf)

    if args.gossip:
        if args.gossip < 2:
            ap.error("--gossip needs N >= 2 peers")
        if args.ops < 0:
            ap.error("--ops needs R >= 0")
        if args.reads < 0:
            ap.error("--reads needs R >= 0")
        if args.kill_sweep < 1:
            ap.error("--kill-sweep needs K >= 1")
        if args.window is not None and args.window < 0:
            ap.error("--window needs N >= 0")
        return gossip_demo(args.gossip, args.objects, args.platform,
                           divergence=args.divergence,
                           fleet_port=args.fleet_port,
                           ops_rate=args.ops, reads_rate=args.reads,
                           gc_enabled=args.gc,
                           gc_interval=args.gc_interval,
                           gc_hysteresis=args.gc_hysteresis,
                           digest_tree=args.digest_tree,
                           zipf_s=args.zipf, burst_len=args.burst,
                           durable_dir=args.durable,
                           kill_sweep=args.kill_sweep,
                           window=args.window, heat=args.heat)

    if args.window is not None and args.window < 0:
        ap.error("--window needs N >= 0")

    if args.role != "demo":
        if not args.port:
            ap.error("server/client roles need --port")
        status = peer(args.role, args.port, args.objects, args.platform,
                      full_state=args.full_state, divergence=args.divergence,
                      metrics_port=args.metrics_port, linger_s=args.linger,
                      window=args.window)
        return 0 if status == "CONVERGED" else 1

    # demo: spawn both peers as real OS processes
    import subprocess

    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]

    base = [sys.executable, os.path.abspath(__file__)]
    extra = ["--port", str(port), "--objects", str(args.objects),
             "--divergence", str(args.divergence)]
    if args.full_state:
        extra += ["--full-state"]
    if args.platform:
        extra += ["--platform", args.platform]
    if args.window is not None:
        extra += ["--window", str(args.window)]
    srv_extra = list(extra)
    if args.metrics_port is not None:
        # one exporter per process; in demo mode the server peer gets it
        srv_extra += ["--metrics-port", str(args.metrics_port),
                      "--linger", str(args.linger)]
    srv = subprocess.Popen(base + ["server"] + srv_extra)
    cli = subprocess.Popen(base + ["client"] + extra)
    rc = srv.wait() | cli.wait()
    print("demo:", "CONVERGED" if rc == 0 else "DIVERGED/FAILED")
    return rc


if __name__ == "__main__":
    sys.exit(main())
