"""Every wire-friendly type through the bulk codec — the whole zoo.

The reference's replication contract is one sentence: serialize state,
transport however you like, merge on the other side
(`/root/reference/src/lib.rs:62-83`).  This example runs that loop for
EVERY batch type with a native wire leg — GCounter, PNCounter, VClock,
GSet, LWWReg, MVReg, ORSWOT, Map<K, MVReg>, Map<K, Orswot> — in one
pass: site A and site B each build divergent fleets, exchange
``to_wire`` blobs (byte-identical to ``to_binary`` of the scalars, so
either side could be a plain scalar peer), ``from_wire`` + ``merge`` on
the dense engine, and verify against the scalar oracle.

Run it:

    python examples/wire_zoo.py                  # CPU backend
    python examples/wire_zoo.py --platform tpu   # on real hardware
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

_args = argparse.ArgumentParser()
_args.add_argument("--platform", default="cpu",
                   help="JAX platform (default cpu; backend DISCOVERY can "
                        "hang when a remote accelerator is unreachable, so "
                        "the example never auto-detects)")
jax.config.update("jax_platforms", _args.parse_args().platform)

from crdt_tpu import to_binary
from crdt_tpu.batch import OrswotBatch
from crdt_tpu.batch.gcounter_batch import GCounterBatch
from crdt_tpu.batch.gset_batch import GSetBatch
from crdt_tpu.batch.lwwreg_batch import LWWRegBatch
from crdt_tpu.batch.map_batch import MapBatch
from crdt_tpu.batch.mvreg_batch import MVRegBatch
from crdt_tpu.batch.pncounter_batch import PNCounterBatch
from crdt_tpu.batch.vclock_batch import VClockBatch
from crdt_tpu.batch.val_kernels import MVRegKernel, OrswotKernel
from crdt_tpu.config import CrdtConfig
from crdt_tpu.scalar.gcounter import GCounter
from crdt_tpu.scalar.gset import GSet
from crdt_tpu.scalar.lwwreg import LWWReg
from crdt_tpu.scalar.map import Map
from crdt_tpu.scalar.mvreg import MVReg
from crdt_tpu.scalar.orswot import Orswot
from crdt_tpu.scalar.pncounter import PNCounter
from crdt_tpu.scalar.vclock import VClock

N = 4  # objects per fleet — tiny so the printout stays readable


def build_sites(cfg):
    """(site_a, site_b): per-type scalar fleets with divergent ops."""

    def counters(actor):
        out = []
        for i in range(N):
            p = PNCounter()
            for _ in range(i + actor + 1):
                p.apply(p.inc(actor))
            if i % 2:
                p.apply(p.dec(actor))
            out.append(p)
        return out

    def gcounters(actor):
        out = []
        for i in range(N):
            g = GCounter()
            for _ in range(i + 1):
                g.apply(g.inc(actor))
            out.append(g)
        return out

    def clocks(actor):
        return [VClock({actor: i + 1}) for i in range(N)]

    def gsets(actor):
        out = []
        for i in range(N):
            s = GSet()
            s.insert(actor * 10 + i)
            out.append(s)
        return out

    def lwws(actor):
        # markers are (globally unique) timestamps; actor breaks ties
        return [LWWReg(val=actor * 100 + i, marker=2 * i + actor)
                for i in range(N)]

    def mvregs(actor):
        out = []
        for i in range(N):
            r = MVReg()
            r.apply(r.set(actor * 100 + i, r.read().derive_add_ctx(actor)))
            out.append(r)
        return out

    def orswots(actor):
        out = []
        for i in range(N):
            s = Orswot()
            s.apply(s.add(actor * 10 + i, s.value().derive_add_ctx(actor)))
            out.append(s)
        return out

    def map_mvregs(actor):
        out = []
        for i in range(N):
            m = Map(MVReg)
            ctx = m.get(i).derive_add_ctx(actor)
            m.apply(m.update(i, ctx,
                             lambda v, c, _v=actor * 100 + i: v.set(_v, c)))
            out.append(m)
        return out

    def map_orswots(actor):
        out = []
        for i in range(N):
            m = Map(Orswot)
            ctx = m.get(i).derive_add_ctx(actor)
            m.apply(m.update(i, ctx,
                             lambda v, c, _m=actor * 10 + i: v.add(_m, c)))
            out.append(m)
        return out

    def site(actor):
        return {
            "GCounter": gcounters(actor),
            "PNCounter": counters(actor),
            "VClock": clocks(actor),
            "GSet": gsets(actor),
            "LWWReg": lwws(actor),
            "MVReg": mvregs(actor),
            "Orswot": orswots(actor),
            "Map<K,MVReg>": map_mvregs(actor),
            "Map<K,Orswot>": map_orswots(actor),
        }

    return site(1), site(2)


def main():
    from crdt_tpu.utils.interning import Universe

    cfg = CrdtConfig(num_actors=4, member_capacity=8, deferred_capacity=4,
                     mv_capacity=4, key_capacity=4)
    uni = Universe.identity(cfg)
    site_a, site_b = build_sites(cfg)

    batch_of = {
        "GCounter": lambda blobs: GCounterBatch.from_wire(blobs, uni),
        "PNCounter": lambda blobs: PNCounterBatch.from_wire(blobs, uni),
        "VClock": lambda blobs: VClockBatch.from_wire(blobs, uni),
        "GSet": lambda blobs: GSetBatch.from_wire(blobs, uni, 64),
        "LWWReg": lambda blobs: LWWRegBatch.from_wire(blobs, uni),
        "MVReg": lambda blobs: MVRegBatch.from_wire(blobs, uni),
        "Orswot": lambda blobs: OrswotBatch.from_wire(blobs, uni),
        "Map<K,MVReg>": lambda blobs: MapBatch.from_wire(
            blobs, uni, MVRegKernel.from_config(cfg)),
        "Map<K,Orswot>": lambda blobs: MapBatch.from_wire(
            blobs, uni, OrswotKernel.from_config(cfg)),
    }

    for name, fleet_a in site_a.items():
        fleet_b = site_b[name]
        # A and B exchange wire blobs (what would cross the socket) and
        # merge the peer's state on the dense engine
        wire_a = [to_binary(s) for s in fleet_a]
        wire_b = [to_binary(s) for s in fleet_b]
        ba = batch_of[name](wire_b).merge(batch_of[name](wire_a))
        bb = batch_of[name](wire_a).merge(batch_of[name](wire_b))

        # scalar oracle: pairwise merge of the scalar fleets
        oracle = []
        for sa, sb in zip(fleet_a, fleet_b):
            sa.merge(sb)  # LWWReg's funky merge may raise on conflicts
            oracle.append(sa)

        got_a = ba.to_scalar(uni)
        got_b = bb.to_scalar(uni)
        assert got_a == got_b == oracle, f"{name}: divergence"
        # egress is byte-identical to the scalar encoder, so the merged
        # state replicates onward to ANY peer, dense or scalar
        assert ba.to_wire(uni) == [to_binary(s) for s in oracle]
        print(f"{name:>14}: converged, byte-faithful "
              f"({sum(map(len, wire_a)) + sum(map(len, wire_b))} wire bytes)")

    print("wire zoo: all", len(site_a), "type families converged")


if __name__ == "__main__":
    main()
