"""Pretty-print demo — the counterpart of the reference's only example
(`/root/reference/examples/pprint.rs:1-21`): build a VClock and a
multi-value register, show their Display forms, then do the same for a
batched ORSWOT fleet via the host-side pretty-printer.

Run:  PYTHONPATH=. python examples/pprint.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from crdt_tpu import MVReg, VClock


def main():
    # VClock Display — `(actor->count, ...)` (`vclock.rs:73-84`)
    vclock = VClock()
    vclock.witness(31231, 2)
    vclock.witness(4829, 9)
    vclock.witness(87132, 32)
    print(f"vclock:\t{vclock}")

    # MVReg Display — `|val@(clock), ...|` (`mvreg.rs:61-72`); two
    # concurrent writers leave both values visible
    reg = MVReg()
    op1 = reg.set("some val", reg.read().derive_add_ctx(9742820))
    op2 = reg.set("some other val", reg.read().derive_add_ctx(648572))
    reg.apply(op1)
    reg.apply(op2)
    print(f"reg:\t{reg}")

    # batch-engine parity: pack a small ORSWOT fleet onto the device path
    # and pretty-print each object from the SoA buffers (host-side Display,
    # SURVEY.md §5 "tracing")
    import jax

    # examples run host-side by default (a remote-TPU tunnel adds ~70ms
    # per dispatch); set CRDT_EXAMPLE_PLATFORM to override
    jax.config.update(
        "jax_platforms", os.environ.get("CRDT_EXAMPLE_PLATFORM", "cpu")
    )

    from crdt_tpu import Orswot
    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.utils.interning import Universe

    uni = Universe(CrdtConfig(num_actors=4, member_capacity=8, deferred_capacity=4))
    fleet = []
    for items in (["apple", "pear"], ["plum"]):
        s = Orswot()
        for actor, member in enumerate(items):
            s.apply(s.add(member, s.value().derive_add_ctx(actor)))
        fleet.append(s)
    batch = OrswotBatch.from_scalar(fleet, uni)
    for i, scalar in enumerate(batch.to_scalar(uni)):
        print(f"orswot[{i}]:\t{{{', '.join(sorted(map(str, scalar.value().val)))}}}")


if __name__ == "__main__":
    main()
