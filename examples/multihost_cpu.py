"""Two-PROCESS distributed lattice join — the multi-host path for real.

The reference simulates replicas in one process
(`/root/reference/test/orswot.rs:37-76`); ``tests/test_sharding.py``
does the same over a virtual device mesh.  This example crosses an
actual process boundary: two OS processes (each holding 4 virtual CPU
devices — stand-ins for two hosts' accelerators) join one
``jax.distributed`` runtime, and the stock collective join runs over
the 2-process global mesh with XLA's cross-process collectives (Gloo on
CPU; ICI/DCN on TPU pods) moving the state.  Nothing in the collective
layer changes — that is the point.

Two topologies, both verified against the scalar N-way oracle:

* ``replicas``  — the 8 replica rows span BOTH processes; the join's
  all-gather itself crosses the process boundary (the comm-backend
  stress case).
* ``hybrid``    — objects partition ACROSS processes (the DCN tier:
  zero cross-process join traffic, each object's merge is independent)
  while each process's 4 replica rows join on its own devices (the
  ICI tier) via ``object_axis=`` — the layout
  ``crdt_tpu.parallel.multihost`` advertises for pods.

Run:  python examples/multihost_cpu.py            # spawns both peers
      python examples/multihost_cpu.py --topology hybrid
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_PROCS = 2
DEVS_PER_PROC = 4


def worker(args) -> int:
    # both env var AND config update: the env must be set before the
    # first backend init; the config update defeats the preloaded axon
    # plugin (reports/TPU_TUNNEL_STATUS.md)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={DEVS_PER_PROC}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from crdt_tpu import Orswot
    from crdt_tpu.batch import OrswotBatch
    from crdt_tpu.config import CrdtConfig
    from crdt_tpu.parallel import (
        allgather_join_orswot,
        initialize,
        local_shard,
        make_multihost_mesh,
    )
    from crdt_tpu.utils.interning import Universe

    topo = initialize(
        coordinator_address=f"127.0.0.1:{args.coordinator_port}",
        num_processes=N_PROCS,
        process_id=args.process_id,
    )
    assert topo["processes"] == N_PROCS, topo
    pid = args.process_id

    # IDENTITY universe: dense index == value.  Cross-host joins mix
    # dense planes built on different hosts, so the interning must be
    # deterministic and shared — per-host insertion-order registries
    # would map DIFFERENT actors to the SAME dense id (see
    # parallel/multihost.py docstring).
    uni = Universe.identity(CrdtConfig(num_actors=8, member_capacity=16,
                                       deferred_capacity=8))
    n_objects = args.objects

    def build_fleet(n_rows, first_actor, obj_slice):
        """Replica rows over the SAME objects; deterministic per seed so
        every process can rebuild any row for the oracle."""
        rows = []
        for r in range(n_rows):
            rng = np.random.RandomState(1000 + first_actor + r)
            row = []
            for i in range(n_objects):
                o = Orswot()
                for _ in range(int(rng.randint(1, 4))):
                    o.apply(o.add(int(rng.randint(0, 12)),
                                  o.value().derive_add_ctx(first_actor + r)))
                row.append(o)
            rows.append(row[obj_slice])
        return rows

    if args.topology == "replicas":
        # 8 replica rows, 4 per process, full object range each; the
        # all-gather crosses the process boundary
        mesh = make_multihost_mesh({"replicas": N_PROCS * DEVS_PER_PROC})
        mine = build_fleet(DEVS_PER_PROC, first_actor=pid * DEVS_PER_PROC,
                           obj_slice=slice(None))
        local = [OrswotBatch.from_scalar(row, uni) for row in mine]
        import jax.numpy as jnp

        local_np = jax.tree_util.tree_map(
            lambda *xs: np.asarray(jnp.stack(xs)), *local
        )
        from jax.sharding import NamedSharding, PartitionSpec as P

        stacked = jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(
                NamedSharding(mesh, P("replicas", *([None] * (x.ndim - 1)))), x
            ),
            local_np,
        )
        joined = allgather_join_orswot(stacked, mesh, axis="replicas")
        # oracle: every process can rebuild all 8 rows deterministically
        expected = [Orswot() for _ in range(n_objects)]
        for p in range(N_PROCS):
            for row in build_fleet(DEVS_PER_PROC, first_actor=p * DEVS_PER_PROC,
                                   obj_slice=slice(None)):
                for e, o in zip(expected, row):
                    e.merge(o)
        want_sets = [sorted(e.value().val) for e in _plunge(expected)]
        # verify every replica row THIS process holds (the collective's
        # postcondition: each row carries the identical global join)
        planes = (joined.clock, joined.ids, joined.dots, joined.d_ids,
                  joined.d_clocks)
        n_local_rows = len(planes[0].addressable_shards)
        assert n_local_rows == DEVS_PER_PROC
        for s in range(n_local_rows):
            shard = OrswotBatch(**dict(zip(
                ("clock", "ids", "dots", "d_ids", "d_clocks"),
                (np.asarray(p.addressable_shards[s].data)[0] for p in planes),
            )))
            plunged = shard.merge(OrswotBatch.zeros(n_objects, uni))
            got_sets = [sorted(o.value().val) for o in plunged.to_scalar(uni)]
            assert got_sets == want_sets, f"proc {pid} shard {s} diverged"
    else:  # hybrid
        # objects split across processes (DCN tier, zero join traffic);
        # 4 replica rows join within each process's devices (ICI tier)
        mesh = make_multihost_mesh(
            {"replicas": DEVS_PER_PROC}, {"objects": N_PROCS}
        )
        my_objs = local_shard(n_objects, N_PROCS, pid)
        mine = build_fleet(DEVS_PER_PROC, first_actor=0, obj_slice=my_objs)
        local = [OrswotBatch.from_scalar(row, uni) for row in mine]
        import jax.numpy as jnp

        local_np = jax.tree_util.tree_map(
            lambda *xs: np.asarray(jnp.stack(xs)), *local
        )
        from jax.sharding import NamedSharding, PartitionSpec as P

        stacked = jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(
                NamedSharding(
                    mesh, P("replicas", "objects", *([None] * (x.ndim - 2)))
                ),
                x,
            ),
            local_np,
        )
        joined = allgather_join_orswot(
            stacked, mesh, axis="replicas", object_axis="objects"
        )
        n_local = local_np.clock.shape[1]
        expected = [Orswot() for _ in range(n_local)]
        for row in mine:
            for e, o in zip(expected, row):
                e.merge(o)
        # each process verifies ITS object partition from its own shards
        shard0 = jax.tree_util.tree_map(
            lambda x: np.asarray(x.addressable_shards[0].data)[0],
            (joined.clock, joined.ids, joined.dots, joined.d_ids,
             joined.d_clocks),
        )
        got = OrswotBatch(
            clock=shard0[0], ids=shard0[1], dots=shard0[2],
            d_ids=shard0[3], d_clocks=shard0[4],
        )
        n_shard = shard0[0].shape[0]
        plunged = got.merge(OrswotBatch.zeros(n_shard, uni))
        got_sets = [sorted(o.value().val) for o in plunged.to_scalar(uni)]
        want = [sorted(e.value().val)
                for e in _plunge(expected)][: n_shard]
        assert got_sets == want, f"proc {pid} hybrid shard diverged"

    print(f"proc {pid}: topology={args.topology} objects={n_objects} "
          f"processes={topo['processes']} MULTIHOST OK", flush=True)
    return 0


def _plunge(states):
    for s in states:
        from crdt_tpu import Orswot

        s.merge(Orswot())
    return states


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--coordinator-port", type=int, default=0)
    ap.add_argument("--objects", type=int, default=8)
    ap.add_argument("--topology", default="replicas",
                    choices=["replicas", "hybrid"])
    args = ap.parse_args()

    if args.process_id is not None:
        return worker(args)

    # demo: spawn both processes
    import socket
    import subprocess

    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]

    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--process-id", str(i), "--coordinator-port", str(port),
             "--objects", str(args.objects), "--topology", args.topology]
        )
        for i in range(N_PROCS)
    ]
    rc = 0
    for p in procs:
        rc |= p.wait()
    print("demo:", "MULTIHOST OK" if rc == 0 else "FAILED")
    return rc


if __name__ == "__main__":
    sys.exit(main())
